"""distlint rules DL001-DL018 (catalog + rationale: docs/LINTS.md).

Each rule targets a failure class this codebase has actually hit or is
structurally exposed to: blocking calls on the serving spine, unlocked
shared state, silent exception swallowing, proto/wire drift, metric rot,
and host-side work leaking into the per-token decode loop (DL001-DL007,
single-module or table-driven), plus the interprocedural layer
(tools/lint/callgraph.py + threads.py): cross-thread write analysis,
lock-order cycles, internal-API call conformance, fault-point drift, and
config-key drift (DL008-DL012), plus the v3 lifecycle layer: exactly-once
registry resolution, exception-edge resource pairing, wire-handler
exhaustiveness, and fault-point test coverage (DL015-DL018).
"""

from __future__ import annotations

import ast
import importlib.util
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.lint import proto as protodef
from tools.lint.core import (
    Finding,
    Module,
    Rule,
    ScopedVisitor,
    dotted_name,
    register,
)

SERVING_PREFIX = "distributed_inference_server_tpu/serving/"

#: calls that block the calling thread, by dotted name
BLOCKING_DOTTED = frozenset({
    "time.sleep",
    "jax.device_get",
    "subprocess.run",
    "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection",
    "urllib.request.urlopen",
})
#: method names that block regardless of receiver
BLOCKING_ATTRS = frozenset({"block_until_ready"})
#: method names that block and are therefore forbidden un-awaited in
#: ``async def`` bodies (threading.Event.wait, Lock.acquire, Future.result)
ASYNC_BLOCKING_ATTRS = frozenset({"wait", "acquire", "result"})


def _is_blocking_call(node: ast.Call) -> Optional[str]:
    dotted = dotted_name(node.func)
    if dotted in BLOCKING_DOTTED:
        return dotted
    if isinstance(node.func, ast.Attribute) and node.func.attr in BLOCKING_ATTRS:
        return f".{node.func.attr}()"
    return None


# ---------------------------------------------------------------------------
# DL001 — blocking calls on async / serving-spine paths
# ---------------------------------------------------------------------------


@register
class DL001(Rule):
    """Blocking calls inside ``async def`` (anywhere) or raw ``time.sleep``
    / device syncs anywhere under ``serving/`` — the serving spine's
    threads must park on ``Event.wait`` (interruptible, shutdown-aware)
    and its coroutines on ``asyncio.sleep``/executors."""

    name = "DL001"
    title = "blocking call on an async or serving-spine path"
    severity = "P0"

    def check(self, module: Module) -> Iterable[Finding]:
        rule = self
        findings: List[Finding] = []
        in_serving = module.path.startswith(SERVING_PREFIX)

        class V(ScopedVisitor):
            def __init__(self) -> None:
                super().__init__()
                self._awaited: Set[int] = set()

            def visit_Await(self, node: ast.Await) -> None:
                self._awaited.add(id(node.value))
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call) -> None:
                blocked = _is_blocking_call(node)
                if self.in_async and id(node) not in self._awaited:
                    name = blocked
                    if (name is None and isinstance(node.func, ast.Attribute)
                            and node.func.attr in ASYNC_BLOCKING_ATTRS):
                        name = f".{node.func.attr}()"
                    if name is not None:
                        findings.append(rule.finding(
                            module, node,
                            f"blocking call {name} inside async def "
                            f"{self.func_name} — await an async equivalent "
                            "or offload via run_in_executor",
                            context=self.qualname,
                        ))
                elif in_serving and blocked is not None:
                    findings.append(rule.finding(
                        module, node,
                        f"blocking call {blocked} on the serving spine — "
                        "use Event.wait (shutdown-aware) or move off the "
                        "dispatch path; suppress with a justification if "
                        "this thread legitimately sleeps",
                        context=self.qualname,
                        severity="P1",
                    ))
                self.generic_visit(node)

        V().visit(module.tree)
        return findings


# ---------------------------------------------------------------------------
# DL002 — mutation of lock-guarded shared state outside the lock
# ---------------------------------------------------------------------------

_LOCK_FACTORY_RE = re.compile(r"(^|\.)(Lock|RLock|Condition)$")
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "add", "clear", "update",
    "setdefault", "sort", "reverse",
})


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> "X" (else None)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _mutated_attrs(stmt: ast.AST) -> Set[str]:
    """self attributes mutated by one statement: assignment to ``self.X``
    or ``self.X[...]``, ``self.X <op>= ...``, or ``self.X.<mutator>(...)``."""
    out: Set[str] = set()

    def target_attr(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                target_attr(el)
            return
        a = _self_attr(t)
        if a is not None:
            out.add(a)
            return
        if isinstance(t, ast.Subscript):
            a = _self_attr(t.value)
            if a is not None:
                out.add(a)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            target_attr(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        target_attr(stmt.target)
    elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            a = _self_attr(f.value)
            if a is not None:
                out.add(a)
    return out


def _with_locks(node: ast.AST, lock_attrs: Set[str]) -> Set[str]:
    """Lock attrs entered by a With statement (``with self._lock: ...``)."""
    out: Set[str] = set()
    if isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            a = _self_attr(item.context_expr)
            if a is not None and a in lock_attrs:
                out.add(a)
    return out


@register
class DL002(Rule):
    """For classes that own a ``threading.Lock``/``RLock``/``Condition``:
    any attribute ever mutated under the lock is *guarded*; mutating a
    guarded attribute outside a ``with self.<lock>:`` block (outside
    ``__init__``) is a data race waiting for load.

    Convention: methods named ``*_locked`` declare "caller holds the
    lock" and are exempt — the analysis is intra-procedural and cannot
    see the caller's ``with`` block."""

    name = "DL002"
    title = "guarded shared state mutated outside its lock"
    severity = "P1"

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(module.tree)
                    if isinstance(n, ast.ClassDef)]:
            findings.extend(self._check_class(module, cls))
        return findings

    def _methods(self, cls: ast.ClassDef):
        return [n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _check_class(self, module: Module,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        lock_attrs: Set[str] = set()
        for meth in self._methods(cls):
            for stmt in ast.walk(meth):
                if not isinstance(stmt, ast.Assign):
                    continue
                if not isinstance(stmt.value, ast.Call):
                    continue
                if not _LOCK_FACTORY_RE.search(dotted_name(stmt.value.func)):
                    continue
                for t in stmt.targets:
                    a = _self_attr(t)
                    if a is not None:
                        lock_attrs.add(a)
        if not lock_attrs:
            return []

        # pass 1: attrs mutated while holding any of this class's locks
        guarded: Set[str] = set()
        for meth in self._methods(cls):
            for attr, _node, held in self._iter_mutations(meth, lock_attrs):
                if held:
                    guarded.add(attr)
        guarded -= lock_attrs
        if not guarded:
            return []

        # pass 2: mutations of guarded attrs with no lock held
        findings: List[Finding] = []
        for meth in self._methods(cls):
            if meth.name == "__init__" or meth.name.endswith("_locked"):
                continue
            for attr, node, held in self._iter_mutations(meth, lock_attrs):
                if attr in guarded and not held:
                    findings.append(self.finding(
                        module, node,
                        f"self.{attr} is mutated under "
                        f"{'/'.join(sorted(lock_attrs))} elsewhere but "
                        f"written here without the lock",
                        context=f"{cls.name}.{meth.name}",
                    ))
        return findings

    def _iter_mutations(self, meth, lock_attrs: Set[str]):
        """Yield (attr, node, lock_held) for each self-attr mutation in the
        method body. Nested function defs are skipped: closures run later,
        on other threads, under their own discipline."""

        def walk(node: ast.AST, held: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                child_held = held or bool(_with_locks(child, lock_attrs))
                for attr in _mutated_attrs(child):
                    yield attr, child, child_held
                yield from walk(child, child_held)

        yield from walk(meth, False)


# ---------------------------------------------------------------------------
# DL003 — lock held across await / blocking call
# ---------------------------------------------------------------------------

_LOCKISH_NAME_RE = re.compile(r"lock|mutex|cond|(^|_)cv$", re.IGNORECASE)


@register
class DL003(Rule):
    """Inside ``with <lock>:`` — where the context expression *names* a
    lock (``_lock``, ``_cv``, ``mutex`` ...) — an ``await`` or a blocking
    call serializes every other thread/task on that lock for the full
    duration. Calls on the lock object itself (``cv.wait``) are exempt:
    Condition.wait releases the lock."""

    name = "DL003"
    title = "lock held across await or blocking call"
    severity = "P0"

    _HELD_BLOCKING_ATTRS = frozenset(
        {"wait", "join", "acquire", "result"} | set(BLOCKING_ATTRS)
    )

    def check(self, module: Module) -> Iterable[Finding]:
        rule = self
        findings: List[Finding] = []

        class V(ScopedVisitor):
            def _visit_with(self, node) -> None:
                lock_exprs = [
                    item.context_expr for item in node.items
                    if _LOCKISH_NAME_RE.search(
                        dotted_name(item.context_expr).rsplit(".", 1)[-1])
                ]
                if lock_exprs:
                    self._scan_body(node, lock_exprs)
                self.generic_visit(node)

            visit_With = _visit_with
            visit_AsyncWith = _visit_with

            def _scan_body(self, with_node, lock_exprs) -> None:
                lock_dumps = {ast.dump(e) for e in lock_exprs}
                lock_names = " / ".join(dotted_name(e) or "<lock>"
                                        for e in lock_exprs)

                def walk(node: ast.AST) -> None:
                    for child in ast.iter_child_nodes(node):
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)):
                            continue
                        if isinstance(child, ast.Await):
                            findings.append(rule.finding(
                                module, child,
                                f"await while holding {lock_names}",
                                context=self.qualname,
                            ))
                        elif isinstance(child, ast.Call):
                            self._check_call(child, lock_dumps, lock_names)
                        walk(child)

                for stmt in with_node.body:
                    walk(stmt)

            def _check_call(self, node: ast.Call, lock_dumps,
                            lock_names) -> None:
                blocked = _is_blocking_call(node)
                if (blocked is None
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in rule._HELD_BLOCKING_ATTRS):
                    # calls on the held lock itself are the exemption
                    if ast.dump(node.func.value) in lock_dumps:
                        return
                    blocked = f".{node.func.attr}()"
                if blocked is not None:
                    findings.append(rule.finding(
                        module, node,
                        f"blocking call {blocked} while holding "
                        f"{lock_names}",
                        context=self.qualname,
                    ))

        V().visit(module.tree)
        return findings


# ---------------------------------------------------------------------------
# DL004 — silently swallowed broad excepts
# ---------------------------------------------------------------------------

_LOG_METHODS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical", "log",
    "warn",
})
_COUNTERISH_RE = re.compile(r"drop|err|fail|count|total", re.IGNORECASE)


@register
class DL004(Rule):
    """``except Exception`` / bare ``except`` whose handler neither
    re-raises, nor logs, nor increments an error counter, nor *uses* the
    caught exception (forwarding ``e`` into a sink/callback/state counts
    as handling) — the error vanishes and only a soak test will find it."""

    name = "DL004"
    title = "broad except swallows the error silently"
    severity = "P1"

    def check(self, module: Module) -> Iterable[Finding]:
        rule = self
        findings: List[Finding] = []

        class V(ScopedVisitor):
            def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
                if rule._is_broad(node.type) and not rule._handled(node):
                    kind = ("bare except" if node.type is None
                            else "except Exception")
                    findings.append(rule.finding(
                        module, node,
                        f"{kind} swallows the error: add logging, an "
                        "errors_total increment, or a re-raise (or forward "
                        "the exception into the failure path)",
                        context=self.qualname,
                    ))
                self.generic_visit(node)

        V().visit(module.tree)
        return findings

    @staticmethod
    def _is_broad(t: Optional[ast.expr]) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(DL004._is_broad(el) for el in t.elts)
        return (isinstance(t, ast.Name)
                and t.id in ("Exception", "BaseException"))

    @staticmethod
    def _handled(handler: ast.ExceptHandler) -> bool:
        var = handler.name
        for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Name) and node.id == var:
                return True  # exception object forwarded / recorded
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func)
                if isinstance(node.func, ast.Attribute):
                    if node.func.attr in _LOG_METHODS:
                        return True
                    if node.func.attr == "inc":
                        return True
                if "record_" in dotted or "metric" in dotted:
                    return True
                if dotted.startswith("warnings.warn"):
                    return True
            if (isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Add)):
                tgt = node.target
                if (isinstance(tgt, ast.Attribute)
                        and _COUNTERISH_RE.search(tgt.attr)):
                    return True  # fail-open counter (e.g. otlp dropped)
        return False


# ---------------------------------------------------------------------------
# DL005 — proto <-> protowire drift
# ---------------------------------------------------------------------------


def compare_wire_schema(
    schema: protodef.ProtoSchema,
    messages: Dict[str, Dict[int, Tuple[str, str, str]]],
    enums: Dict[str, Dict[int, Optional[str]]],
) -> List[Tuple[str, str]]:
    """Cross-check the parsed proto schema against protowire's tables.
    Returns ``(anchor, message)`` pairs; anchor is the message/enum name
    the finding attaches to. Pure so tests can inject drifted tables."""
    out: List[Tuple[str, str]] = []

    for name in sorted(set(schema.messages) - set(messages)):
        out.append((name, f"message {name} is in inference.proto but has "
                          "no protowire codec entry"))
    for name in sorted(set(messages) - set(schema.messages)):
        out.append((name, f"protowire codec defines message {name} absent "
                          "from inference.proto"))

    for name in sorted(set(schema.messages) & set(messages)):
        pmsg = schema.messages[name]
        table = messages[name]
        for num in sorted(set(pmsg.fields) - set(table)):
            f = pmsg.fields[num]
            out.append((name, f"{name}: proto field {f.name} = {num} "
                              "missing from the protowire table"))
        for num in sorted(set(table) - set(pmsg.fields)):
            out.append((name, f"{name}: protowire field number {num} "
                              f"({table[num][0]!r}) not in inference.proto"))
        for num in sorted(set(pmsg.fields) & set(table)):
            pf = pmsg.fields[num]
            tname, ttype, tcard = table[num]
            if pf.name != tname:
                out.append((name, f"{name}.{num}: name drift — proto "
                                  f"{pf.name!r} vs protowire {tname!r}"))
            kind, expect_type = protodef.resolve_type(schema, name, pf.type)
            if kind == "unknown":
                out.append((name, f"{name}.{pf.name}: unresolvable proto "
                                  f"type {pf.type!r}"))
                continue
            if expect_type != ttype:
                out.append((name, f"{name}.{pf.name}: type drift — proto "
                                  f"{pf.type} (-> {expect_type}) vs "
                                  f"protowire {ttype!r}"))
            # proto3 singular message fields have explicit presence
            expect_card = pf.label
            if kind == "msg" and expect_card == "one":
                expect_card = "opt"
            if expect_card != tcard:
                out.append((name, f"{name}.{pf.name}: cardinality drift — "
                                  f"proto {expect_card!r} vs protowire "
                                  f"{tcard!r}"))

    for name in sorted(set(schema.enums) - set(enums)):
        out.append((name, f"enum {name} missing from protowire ENUMS"))
    for name in sorted(set(enums) - set(schema.enums)):
        out.append((name, f"protowire enum {name} absent from "
                          "inference.proto"))
    for name in sorted(set(schema.enums) & set(enums)):
        penum = schema.enums[name]
        table = enums[name]
        nonzero = {n: v for n, v in penum.values.items() if n != 0}
        for num in sorted(set(nonzero) - set(k for k in table if k != 0)):
            out.append((name, f"enum {name}: value {nonzero[num]} = {num} "
                              "missing from protowire"))
        for num in sorted(set(table) - set(penum.values) - {0}):
            out.append((name, f"enum {name}: protowire value {num} not in "
                              "inference.proto"))
        for num, vname in sorted(nonzero.items()):
            if num in table and table[num] != vname.lower():
                out.append((name, f"enum {name}.{vname}: JSON string drift "
                                  f"— expected {vname.lower()!r}, protowire "
                                  f"has {table[num]!r}"))
    return out


def load_protowire_tables(root: Path):
    """Import serving/protowire.py standalone (stdlib-only module) and
    return its (MESSAGES, ENUMS)."""
    path = (root / "distributed_inference_server_tpu" / "serving"
            / "protowire.py")
    spec = importlib.util.spec_from_file_location("_distlint_protowire", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return mod.MESSAGES, mod.ENUMS


@register
class DL005(Rule):
    """The hand-rolled codec tables in serving/protowire.py must agree
    field-for-field with the authoritative contract in
    serving/inference.proto — field numbers, names, types, cardinality,
    enum values. Drift here corrupts KV handoffs and gRPC payloads
    silently (the varint still decodes — into the wrong thing)."""

    name = "DL005"
    title = "wire drift between inference.proto and protowire.py"
    severity = "P0"
    scope = "project"

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        proto_path = (root / "distributed_inference_server_tpu" / "serving"
                      / "inference.proto")
        wire_rel = "distributed_inference_server_tpu/serving/protowire.py"
        wire_mod = next((m for m in modules if m.path == wire_rel), None)
        if not proto_path.exists() or wire_mod is None:
            return []
        schema = protodef.parse_file(proto_path)
        messages, enums = load_protowire_tables(root)
        findings = []
        for anchor, msg in compare_wire_schema(schema, messages, enums):
            findings.append(Finding(
                rule=self.name, path=wire_rel,
                line=self._anchor_line(wire_mod, anchor),
                message=msg, severity=self.severity, context=anchor,
                line_text=wire_mod.text(self._anchor_line(wire_mod, anchor)),
            ))
        return findings

    @staticmethod
    def _anchor_line(module: Module, name: str) -> int:
        pat = f'"{name}"'
        for i, line in enumerate(module.lines, 1):
            if pat in line:
                return i
        return 1


# ---------------------------------------------------------------------------
# DL006 — metric hygiene
# ---------------------------------------------------------------------------

_METRIC_FACTORIES = frozenset({"Counter", "Gauge", "Histogram", "Summary"})


@register
class DL006(Rule):
    """Every metric registered on MetricsCollector must be emitted by some
    recording method, every public recording method must be called from
    the serving stack, and every ``*.metrics.<attr>`` access must resolve
    to a real collector attribute (no phantom metrics, no typo'd
    emission sites)."""

    name = "DL006"
    title = "metric registered/emitted mismatch"
    severity = "P1"
    scope = "project"

    METRICS_PATH = "distributed_inference_server_tpu/serving/metrics.py"

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        mmod = next((m for m in modules if m.path == self.METRICS_PATH), None)
        if mmod is None:
            return []
        cls = next((n for n in ast.walk(mmod.tree)
                    if isinstance(n, ast.ClassDef)
                    and n.name == "MetricsCollector"), None)
        if cls is None:
            return []

        metric_attrs: Dict[str, ast.AST] = {}
        prom_names: Dict[str, ast.AST] = {}
        findings: List[Finding] = []
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is not None:
            for node in ast.walk(init):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                fname = dotted_name(node.value.func).rsplit(".", 1)[-1]
                if fname not in _METRIC_FACTORIES:
                    continue
                attr = _self_attr(node.targets[0]) if node.targets else None
                if attr is None:
                    continue
                metric_attrs[attr] = node
                args = node.value.args
                if args and isinstance(args[0], ast.Constant) \
                        and isinstance(args[0].value, str):
                    pname = args[0].value
                    if pname in prom_names:
                        findings.append(self.finding(
                            mmod, node,
                            f"duplicate prometheus metric name {pname!r}",
                            context="MetricsCollector.__init__",
                        ))
                    prom_names[pname] = node

        methods = {n.name for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        public_methods = {m for m in methods if not m.startswith("_")}
        # module-level names of metrics.py are legal accesses through a
        # `metrics` module alias (EngineStatus etc.)
        module_names = {n.name for n in mmod.tree.body
                        if isinstance(n, (ast.ClassDef, ast.FunctionDef))}
        allowed = set(metric_attrs) | methods | module_names | {"registry"}

        # reads of self.<metric attr> inside metrics.py (emission sites)
        internal_reads: Set[str] = set()
        for node in ast.walk(cls):
            a = _self_attr(node)
            if a is not None and isinstance(node.ctx, ast.Load):
                internal_reads.add(a)

        # accesses through a receiver *named* metrics, package-wide
        external: Dict[str, List[Tuple[Module, ast.AST]]] = {}
        for mod in modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                recv = node.value
                is_metrics_recv = (
                    (isinstance(recv, ast.Name) and recv.id == "metrics")
                    or (isinstance(recv, ast.Attribute)
                        and recv.attr == "metrics")
                )
                if is_metrics_recv:
                    external.setdefault(node.attr, []).append((mod, node))

        for attr, sites in sorted(external.items()):
            if attr not in allowed:
                mod, node = sites[0]
                findings.append(self.finding(
                    mod, node,
                    f"metrics.{attr} does not exist on MetricsCollector "
                    "(typo'd emission site or unregistered metric)",
                ))

        for attr, node in sorted(metric_attrs.items()):
            if attr not in internal_reads and attr not in external:
                findings.append(self.finding(
                    mmod, node,
                    f"metric self.{attr} is registered but never emitted",
                    context="MetricsCollector.__init__",
                ))

        for meth in sorted(public_methods):
            if meth in ("snapshot", "prometheus_text"):
                continue  # rendering surface, exercised by transports/tests
            if meth not in external:
                node = next(n for n in cls.body
                            if isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))
                            and n.name == meth)
                findings.append(self.finding(
                    mmod, node,
                    f"MetricsCollector.{meth} is never called from the "
                    "serving stack — dead recording surface",
                    context=f"MetricsCollector.{meth}",
                ))
        return findings


# ---------------------------------------------------------------------------
# DL007 — JAX hot-path hygiene in the per-token decode loop
# ---------------------------------------------------------------------------


@register
class DL007(Rule):
    """The per-token emission path in engine/engine.py (HOT_FUNCTIONS)
    runs once per generated token on the host: a ``jnp.*`` call allocates
    device memory / dispatches XLA work there, and an explicit sync
    (``device_get`` / ``block_until_ready`` / ``.item()``) stalls the
    decode pipeline. Device reads belong at the block boundary
    (``np.asarray`` on the block's outputs, once per block)."""

    name = "DL007"
    title = "device work inside the per-token decode loop"
    severity = "P0"

    TARGET = "distributed_inference_server_tpu/engine/engine.py"
    HOT_FUNCTIONS = frozenset({
        "_process_block", "_drain_pending", "_emit_token", "_decode_piece",
        "_flush_pending_text", "_finish",
        # the mixed-step reap (ISSUE 12): runs every mixed dispatch and
        # walks completed prompts through the same emission path — its
        # one np.asarray is the block-boundary read, anything jnp/sync
        # beyond that stalls the mixed pipeline exactly like the decode
        # loop
        "_reap_mixed_prefill",
        # the looped-block reap (kernel looping, docs/PERF.md): runs
        # once per run-to-completion block and settles the device page
        # draw + walks every emitted token — the whole point of the
        # loop is killing host sync, so device work here would undo it
        "_process_loop_block",
    })
    _SYNC_ATTRS = frozenset({"block_until_ready", "item"})

    def check(self, module: Module) -> Iterable[Finding]:
        if module.path != self.TARGET:
            return []
        rule = self
        findings: List[Finding] = []

        class V(ScopedVisitor):
            def visit_Call(self, node: ast.Call) -> None:
                if rule.HOT_FUNCTIONS & set(self._stack):
                    dotted = dotted_name(node.func)
                    bad = None
                    if dotted.startswith("jnp.") \
                            or dotted.startswith("jax.numpy."):
                        bad = f"{dotted} (device allocation/dispatch)"
                    elif dotted == "jax.device_get":
                        bad = "jax.device_get (host sync)"
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in rule._SYNC_ATTRS):
                        bad = f".{node.func.attr}() (host sync)"
                    if bad is not None:
                        findings.append(rule.finding(
                            module, node,
                            f"{bad} inside the per-token decode loop "
                            f"({self.func_name}) — hoist to the block "
                            "boundary",
                            context=self.qualname,
                        ))
                self.generic_visit(node)

        V().visit(module.tree)
        return findings


# ---------------------------------------------------------------------------
# DL008-DL010 — interprocedural rules over the call graph
# (tools/lint/callgraph.py builds the summary, tools/lint/threads.py the
# thread-ownership model; docs/LINTS.md documents both)
# ---------------------------------------------------------------------------


def _summary_and_module(modules: Sequence[Module]):
    from tools.lint import callgraph

    return (callgraph.build_summary(modules),
            {m.path: m for m in modules})


def _anchored(rule: Rule, by_path: Dict[str, Module], path: str,
              lineno: int, message: str, context: str,
              severity: Optional[str] = None) -> Finding:
    mod = by_path.get(path)
    line_text = mod.text(lineno) if mod is not None else ""
    return Finding(rule=rule.name, path=path, line=lineno, message=message,
                   severity=severity or rule.severity, context=context,
                   line_text=line_text)


@register
class DL008(Rule):
    """Thread-confinement: an attribute written from two or more inferred
    thread roots (tools/lint/threads.py) with no lock common to every
    write site is a cross-thread race waiting for load — the class of bug
    behind the ``_fail_all_of``/``submit`` double-resolve (PR 5).

    Honors the ``*_locked`` caller-holds-the-lock convention (such writes
    never break a common lock), skips ``__init__`` (happens-before via
    thread start), skips method-call mutations of threading primitives
    (``Event.clear`` is internally locked), and skips classes marked
    ``# distlint: thread-confined`` (single-owner by design, e.g. the
    engine behind the runner's inbox).

    Suppression is scoped deliberately: an ``ignore[DL008]`` on a WRITE
    site waives exactly that site (every other — and every future — site
    still participates in the analysis), while an ``ignore[DL008]`` on
    the attribute's ``__init__`` declaration waives the whole attribute
    — the visible way to say "this attribute is lock-free by design"
    (e.g. the runner's GIL-atomic pop-first dict protocol)."""

    name = "DL008"
    title = "attribute written from multiple threads with no common lock"
    severity = "P1"
    scope = "project"

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        from tools.lint import callgraph, threads
        from tools.lint.core import suppressed_rules

        summary, by_path = _summary_and_module(modules)
        owners = threads.ownership(summary)

        def site_suppressed(w) -> bool:
            mod = by_path.get(w.path)
            return (mod is not None
                    and self.name in suppressed_rules(mod, w.lineno))

        groups: Dict[Tuple[str, str], list] = {}
        waived: Set[Tuple[str, str]] = set()
        for w in summary.writes:
            if w.cls in summary.class_confined:
                continue
            if w.attr in summary.class_locks.get(w.cls, {}):
                continue
            if w.via_method and w.attr in \
                    summary.class_threadsafe_attrs.get(w.cls, set()):
                continue
            if w.is_init:
                # an ignore on the __init__ declaration is the
                # attribute-wide "lock-free by design" waiver
                if site_suppressed(w):
                    waived.add((w.cls, w.attr))
                continue
            groups.setdefault((w.cls, w.attr), []).append(w)
        findings = []
        for (cls, attr), sites in sorted(groups.items()):
            if (cls, attr) in waived:
                continue
            # a suppressed write site drops out of the analysis alone; a
            # racy site added later is NOT covered by it (the finding
            # re-anchors to the first unsuppressed site)
            sites = [w for w in sites if not site_suppressed(w)]
            roots: Set[str] = set()
            for w in sites:
                roots |= owners.get(w.fn, set())
            if len(roots) < 2:
                continue
            plain = [w for w in sites if not w.caller_locked]
            if not plain:
                continue  # every write declares caller-holds-the-lock
            common = set(plain[0].locks)
            for w in plain[1:]:
                common &= set(w.locks)
            if common:
                continue
            sites_sorted = sorted(sites, key=lambda w: (w.path, w.lineno))
            anchor = sites_sorted[0]
            others = ", ".join(
                f"{w.path.rsplit('/', 1)[-1]}:{w.lineno}"
                for w in sites_sorted[1:6])
            findings.append(_anchored(
                self, by_path, anchor.path, anchor.lineno,
                f"{callgraph.short(cls)}.{attr} is written from "
                f"{len(roots)} threads ({threads.describe_roots(roots)}) "
                f"with no common lock"
                + (f"; other write sites: {others}" if others else "")
                + " — guard every site with one lock, route the write "
                "through the owning thread, or suppress with the "
                "safety argument",
                context=callgraph.short(anchor.fn),
            ))
        return findings


@register
class DL009(Rule):
    """Lock-order cycles across the serving spine: if thread 1 can hold
    lock A while (transitively, through the call graph) acquiring lock B
    and thread 2 the reverse, the fleet can deadlock under load. Also
    flags self-reacquisition of a plain (non-reentrant)
    ``threading.Lock`` through a call chain — a single-thread deadlock."""

    name = "DL009"
    title = "lock-order cycle (potential deadlock)"
    severity = "P1"
    scope = "project"

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        from tools.lint import callgraph, threads

        summary, by_path = _summary_and_module(modules)
        # one fixpoint serves both the cycle and self-reacquire passes
        acq = threads.transitive_acquires(summary)
        edges = threads.lock_order_edges(summary, acq=acq)
        findings = []
        for cycle in threads.find_lock_cycles(edges):
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            example = edges[pairs[0]][0]
            order = " -> ".join(callgraph.short(c) for c in cycle)
            sites = "; ".join(
                f"{callgraph.short(a)}->{callgraph.short(b)} at "
                f"{edges[(a, b)][0][1].rsplit('/', 1)[-1]}:"
                f"{edges[(a, b)][0][2]}"
                for a, b in pairs)
            findings.append(_anchored(
                self, by_path, example[1], example[2],
                f"lock-order cycle {order} -> {callgraph.short(cycle[0])} "
                f"(acquisition sites: {sites}) — pick one global order "
                "or narrow a critical section",
                context=callgraph.short(example[0]),
            ))
        # plain-Lock re-acquisition through a call chain
        seen: Set[Tuple[str, str, int]] = set()
        for caller, callee, held, lineno in summary.calls_under_lock:
            node = summary.functions.get(caller)
            if node is None:
                continue
            for lock, lpath, lline in sorted(acq.get(callee, ())):
                if lock in held \
                        and summary.lock_kinds.get(lock) == "Lock":
                    key = (caller, lock, lineno)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(_anchored(
                        self, by_path, node.path, lineno,
                        f"call while holding {callgraph.short(lock)} "
                        f"reaches {callgraph.short(callee)}, which "
                        f"re-acquires it ({lpath.rsplit('/', 1)[-1]}:"
                        f"{lline}) — a plain Lock self-deadlocks",
                        context=callgraph.short(caller),
                    ))
        return findings


@register
class DL010(Rule):
    """Internal-API call conformance: calls through receivers that
    resolve (by annotation or by the documented receiver-name
    conventions) to the project's cross-thread utility classes are
    checked against the *actual* signatures of those classes — the
    ``Span.event(reason=...)`` TypeError that turned PR 5's invisible
    redispatch into a client-visible failure becomes a lint error."""

    name = "DL010"
    title = "call does not conform to the target's actual signature"
    severity = "P0"
    scope = "project"

    PKG = "distributed_inference_server_tpu"
    #: (module path, class) -> receiver names that conventionally hold an
    #: instance (used when annotation-driven typing can't see the type)
    TARGETS: Dict[Tuple[str, str], frozenset] = {
        (f"{PKG}/utils/tracing.py", "Span"):
            frozenset({"span", "engine_span"}),
        (f"{PKG}/utils/tracing.py", "Tracer"): frozenset({"tracer"}),
        (f"{PKG}/serving/metrics.py", "MetricsCollector"):
            frozenset({"metrics"}),
        (f"{PKG}/serving/faults.py", "FaultSet"): frozenset(),
    }
    #: module whose *functions* are validated when called via its alias
    FUNC_MODULES = (f"{PKG}/serving/faults.py",)

    @staticmethod
    def _sig_errors(sig, call) -> List[str]:
        if call.has_star or call.has_kwstar:
            return []  # splats are untypable statically
        errs = []
        if call.n_pos > len(sig.pos) and not sig.vararg:
            errs.append(f"takes {len(sig.pos)} positional argument(s), "
                        f"got {call.n_pos}")
        kwonly = {n for n, _ in sig.kwonly}
        if not sig.kwarg:
            for kw in call.kwnames:
                if kw not in sig.pos and kw not in kwonly:
                    errs.append(f"unexpected keyword argument {kw!r}")
        n_required = len(sig.pos) - sig.n_defaults
        bound_pos = set(sig.pos[:min(call.n_pos, len(sig.pos))])
        for name in sig.pos[:n_required]:
            if name not in bound_pos and name not in call.kwnames:
                errs.append(f"missing required argument {name!r}")
        for name, has_default in sig.kwonly:
            if not has_default and name not in call.kwnames:
                errs.append(
                    f"missing required keyword-only argument {name!r}")
        for kw in call.kwnames:
            if kw in bound_pos:
                errs.append(f"argument {kw!r} given both positionally "
                            "and by keyword")
        return errs

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        from tools.lint import callgraph

        summary, by_path = _summary_and_module(modules)
        class_ids = {}
        heuristics = {}
        for (path, cls), names in self.TARGETS.items():
            cid = f"{path}::{cls}"
            if cid in summary.class_methods:
                class_ids[cid] = cls
                for n in names:
                    heuristics[n] = cid
        # module-level names per targeted module: accesses through the
        # alias (``metrics.EngineStatus``) are not collector calls
        module_names: Dict[str, Set[str]] = {}
        for path in {p for p, _c in self.TARGETS} | set(self.FUNC_MODULES):
            module_names[path] = set(summary.module_funcs.get(path, ())) | {
                callgraph.short(cid).rsplit(".", 1)[-1]
                for cid in summary.class_methods if cid.startswith(path)
            }
        findings = []
        for call in summary.attr_calls:
            cid = sig = None
            owner = ""
            if call.recv in class_ids:
                cid = call.recv
            elif call.recv.startswith("name:"):
                cid = heuristics.get(call.recv[5:])
            elif call.recv.startswith("mod:"):
                mpath = call.recv[4:]
                if mpath in self.FUNC_MODULES:
                    if call.method in module_names.get(mpath, ()):
                        sig = summary.module_funcs[mpath].get(call.method)
                        owner = mpath.rsplit("/", 1)[-1]
                        if sig is None:
                            continue  # a class accessed via the module
                    else:
                        findings.append(_anchored(
                            self, by_path, call.path, call.lineno,
                            f"{mpath.rsplit('/', 1)[-1]} has no "
                            f"module-level {call.method!r}",
                            context=call.context))
                        continue
            if cid is not None and sig is None:
                owner = callgraph.short(cid)
                mpath = cid.split("::", 1)[0]
                sig = summary.class_methods[cid].get(call.method)
                if sig is None:
                    if (call.method.startswith("__")
                            or call.method in summary.class_members.get(
                                cid, set())
                            or call.method in module_names.get(mpath, ())):
                        continue  # field/property or module-alias access
                    findings.append(_anchored(
                        self, by_path, call.path, call.lineno,
                        f"{owner} has no method {call.method!r} "
                        "(typo'd internal-API call)",
                        context=call.context))
                    continue
            if sig is None:
                continue
            for err in self._sig_errors(sig, call):
                findings.append(_anchored(
                    self, by_path, call.path, call.lineno,
                    f"call to {owner}.{call.method}: {err} (signature: "
                    f"({', '.join(sig.pos) or ''}"
                    f"{', **kw' if sig.kwarg else ''}))",
                    context=call.context))
        return findings


# ---------------------------------------------------------------------------
# DL011 — fault-point drift
# ---------------------------------------------------------------------------

# one dotted-point grammar shared by all four extractors: a catalog
# entry every other regex cannot represent would be a permanently
# "never fired" / "catalogs disagree" finding with no fix
_POINT_PAT = r"[a-z_][a-z0-9_]*(?:\.[a-z_][a-z0-9_]*)+"
_POINT_RE = re.compile(rf"^{_POINT_PAT}$")
_SPEC_POINT_RE = re.compile(
    rf"\b({_POINT_PAT}):(?:nth|prob|times|delay_ms)=")
_DOCS_POINT_ROW_RE = re.compile(rf"^\|\s*`({_POINT_PAT})`\s*\|")
_DOCSTRING_POINT_RE = re.compile(rf"^``({_POINT_PAT})``", re.MULTILINE)


@register
class DL011(Rule):
    """Fault-point drift: every ``faults.fire("...")`` / ``flag`` /
    ``_fault`` literal (and every point named in a fault-spec string)
    must exist in the point catalog — the serving/faults.py module
    docstring and the docs/RESILIENCE.md table — and every cataloged
    point must be fired somewhere, or the resilience documentation and
    the chaos harness drift away from the code they describe."""

    name = "DL011"
    title = "fault-injection point drift vs the point catalog"
    severity = "P1"
    scope = "project"

    FAULTS_PATH = "distributed_inference_server_tpu/serving/faults.py"

    def _fired_points(self, modules: Sequence[Module]):
        """[(point, module, node)] from fire/flag/_fault call literals
        and fault-spec strings (f-string heads included)."""
        out = []
        for mod in modules:
            if mod.path == self.FAULTS_PATH:
                continue  # the registry itself defines, not fires
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    dotted = dotted_name(node.func)
                    tail = dotted.rsplit(".", 1)[-1]
                    if tail in ("fire", "flag") or dotted == "_fault":
                        if node.args and isinstance(node.args[0],
                                                    ast.Constant) \
                                and isinstance(node.args[0].value, str) \
                                and _POINT_RE.match(node.args[0].value):
                            out.append((node.args[0].value, mod, node))
                elif isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    for m in _SPEC_POINT_RE.finditer(node.value):
                        out.append((m.group(1), mod, node))
                elif isinstance(node, ast.JoinedStr):
                    head = node.values[0] if node.values else None
                    if isinstance(head, ast.Constant) \
                            and isinstance(head.value, str):
                        for m in _SPEC_POINT_RE.finditer(head.value):
                            out.append((m.group(1), mod, node))
        return out

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        faults_mod = next(
            (m for m in modules if m.path == self.FAULTS_PATH), None)
        code_catalog = None
        if faults_mod is not None:
            doc = ast.get_docstring(faults_mod.tree) or ""
            code_catalog = set(_DOCSTRING_POINT_RE.findall(doc))
        docs_path = root / "docs" / "RESILIENCE.md"
        docs_catalog = None
        if docs_path.exists():
            docs_catalog = {
                m.group(1)
                for line in docs_path.read_text().splitlines()
                for m in [_DOCS_POINT_ROW_RE.match(line)] if m
            }
        findings = []
        fired = self._fired_points(modules)
        for point, mod, node in fired:
            missing = []
            if code_catalog is not None and point not in code_catalog:
                missing.append("serving/faults.py docstring")
            if docs_catalog is not None and point not in docs_catalog:
                missing.append("docs/RESILIENCE.md point catalog")
            if missing:
                findings.append(self.finding(
                    mod, node,
                    f"fault point {point!r} is not in the "
                    f"{' or the '.join(missing)} — add it to the catalog "
                    "or fix the literal",
                ))
        if faults_mod is not None and code_catalog is not None:
            used = {p for p, _m, _n in fired}

            def anchor_line(point: str) -> int:
                for i, line in enumerate(faults_mod.lines, 1):
                    if point in line:
                        return i
                return 1

            for point in sorted(code_catalog - used):
                findings.append(Finding(
                    rule=self.name, path=faults_mod.path,
                    line=anchor_line(point),
                    message=f"cataloged fault point {point!r} is never "
                            "fired/flagged anywhere — dead catalog entry "
                            "or a lost injection site",
                    severity=self.severity, context="point catalog",
                    line_text=faults_mod.text(anchor_line(point)),
                ))
            if docs_catalog is not None:
                for point in sorted(code_catalog ^ docs_catalog):
                    where = ("docs/RESILIENCE.md"
                             if point in code_catalog
                             else "serving/faults.py docstring")
                    findings.append(Finding(
                        rule=self.name, path=faults_mod.path,
                        line=anchor_line(point),
                        message=f"point catalogs disagree: {point!r} "
                                f"is missing from {where}",
                        severity=self.severity, context="point catalog",
                        line_text=faults_mod.text(anchor_line(point)),
                    ))
        return findings


# ---------------------------------------------------------------------------
# DL013 — span/event-name catalog drift
# ---------------------------------------------------------------------------

# catalog rows in docs/OBSERVABILITY.md: | `name` | span/event | ... |
# (rows whose kind column is anything else — e.g. the flight recorder's
# `timeline` entries — are documentation only, not lint-enforced)
_SPAN_CATALOG_ROW_RE = re.compile(
    r"^\|\s*`([a-z0-9_.<>]+)`\s*\|\s*(span|event)\s*\|")
_SPAN_NAME_RE = re.compile(r"^[a-z_][a-z0-9_.]*$")
_PLACEHOLDER_RE = re.compile(r"<[a-z0-9_]+>")


def _catalog_entry_rx(entry: str) -> re.Pattern:
    """``request.<endpoint>`` -> a regex where each ``<...>`` matches one
    lowercase identifier segment."""
    parts = _PLACEHOLDER_RE.split(entry)
    return re.compile("[a-z0-9_]+".join(re.escape(p) for p in parts) + "$")


@register
class DL013(Rule):
    """Span/event-name catalog drift: every span name started through a
    tracer (``tracer.start("...")`` / ``tracer.span("...")``) and every
    span event name (``span.event("...")`` on the documented span
    receivers) emitted in the package must appear in the
    docs/OBSERVABILITY.md catalog — and every cataloged span/event entry
    must be emitted somewhere (dead-entry detection), or the trace
    documentation and the traces themselves drift apart. Dynamic names
    with a constant f-string head (``f"request.{endpoint}"``) match
    catalog entries whose literal prefix before a ``<placeholder>``
    equals that head."""

    name = "DL013"
    title = "span/event name drift vs the docs/OBSERVABILITY.md catalog"
    severity = "P1"
    scope = "project"

    DOCS = "docs/OBSERVABILITY.md"
    #: receiver terminal names that hold a Tracer (DL010's convention)
    TRACER_RECV = frozenset({"tracer"})
    #: receiver terminal names that hold a Span (DL010's convention)
    SPAN_RECV = frozenset({"span", "engine_span"})

    def _emissions(self, modules: Sequence[Module]):
        """[(name, is_fstring_head, module, node)] for every span start
        and span event emission in the package."""
        out = []
        for mod in modules:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.args):
                    continue
                recv_tail = dotted_name(node.func.value).rsplit(".", 1)[-1]
                is_start = (node.func.attr in ("start", "span")
                            and recv_tail in self.TRACER_RECV)
                is_event = (node.func.attr == "event"
                            and recv_tail in self.SPAN_RECV)
                if not (is_start or is_event):
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    if _SPAN_NAME_RE.match(arg.value):
                        out.append((arg.value, False, mod, node))
                elif isinstance(arg, ast.JoinedStr) and arg.values:
                    head = arg.values[0]
                    if isinstance(head, ast.Constant) \
                            and isinstance(head.value, str) \
                            and head.value:
                        out.append((head.value, True, mod, node))
        return out

    @staticmethod
    def _parse_catalog(path: Path):
        """{entry: (kind, lineno, line_text)} from the docs table."""
        out: Dict[str, Tuple[str, int, str]] = {}
        for i, line in enumerate(path.read_text().splitlines(), 1):
            m = _SPAN_CATALOG_ROW_RE.match(line)
            if m:
                out[m.group(1)] = (m.group(2), i, line.strip())
        return out

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        docs_path = root / self.DOCS
        if not docs_path.exists():
            return []  # no catalog to drift from (fixture roots)
        catalog = self._parse_catalog(docs_path)
        entry_rx = {e: _catalog_entry_rx(e) for e in catalog}
        used: Set[str] = set()
        findings: List[Finding] = []
        for name, is_head, mod, node in self._emissions(modules):
            matched = False
            for entry, rx in entry_rx.items():
                if is_head:
                    # f-string: covered by an entry whose literal prefix
                    # before its first placeholder equals the head
                    if ("<" in entry
                            and entry.split("<", 1)[0] == name):
                        matched = True
                        used.add(entry)
                elif rx.match(name):
                    matched = True
                    used.add(entry)
            if not matched:
                shown = f"{name}{{...}}" if is_head else name
                findings.append(self.finding(
                    mod, node,
                    f"span/event name {shown!r} is not in the "
                    f"{self.DOCS} catalog — add a row "
                    "(| `name` | span/event | ...) or fix the literal",
                ))
        for entry, (kind, lineno, text) in sorted(catalog.items()):
            if entry not in used:
                findings.append(Finding(
                    rule=self.name, path=self.DOCS, line=lineno,
                    message=f"cataloged {kind} name {entry!r} is never "
                            "emitted anywhere in the package — dead "
                            "catalog entry or a lost emission site",
                    severity=self.severity, context="span catalog",
                    line_text=text,
                ))
        return findings


# ---------------------------------------------------------------------------
# DL012 — config-key drift
# ---------------------------------------------------------------------------

_ENV_KEY_RE = re.compile(r"DIS_TPU_([A-Z0-9]+)__([A-Z0-9_]+)")
_CONFIGISH_RE = re.compile(r"(^|_)(cfg|config)$")


@register
class DL012(Rule):
    """Config-key drift: ``config.get(section, key)`` calls, the raw
    ``[section][key]`` / ``(section, key)`` literals inside
    serving/config.py, and every ``DIS_TPU_<SECTION>__<FIELD>`` token in
    the source must name a real ``_SCHEMA`` entry — a typo'd key
    otherwise reads as a KeyError at boot (best case) or a silently
    ignored override (worst case: the env var grammar).

    Receiver discipline for ``.get``: a receiver *typed* (via the call
    graph's annotation resolution) as ``ServerConfig`` is checked
    strictly — unknown sections flag too; a merely config-*named*
    receiver (``cfg``, ``config``, ``*_cfg``) gets the key check only
    when the first argument already names a real section, so a plain
    dict that happens to be called ``cfg`` (``cfg.get("bos_token", "")``
    on tokenizer JSON) never misfires."""

    name = "DL012"
    title = "config key drift vs serving/config.py _SCHEMA"
    severity = "P1"
    scope = "project"

    CONFIG_PATH = "distributed_inference_server_tpu/serving/config.py"
    CONFIG_CLASS = f"{CONFIG_PATH}::ServerConfig"

    @staticmethod
    def _parse_schema(mod: Module) -> Optional[Dict[str, Set[str]]]:
        for node in mod.tree.body:
            if not (isinstance(node, (ast.Assign, ast.AnnAssign))):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            if not any(isinstance(t, ast.Name) and t.id == "_SCHEMA"
                       for t in targets):
                continue
            value = node.value
            if not isinstance(value, ast.Dict):
                return None
            schema: Dict[str, Set[str]] = {}
            for k, v in zip(value.keys, value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Dict)):
                    continue
                schema[k.value] = {
                    fk.value for fk in v.keys
                    if isinstance(fk, ast.Constant)
                    and isinstance(fk.value, str)
                }
            return schema
        return None

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        cfg_mod = next((m for m in modules if m.path == self.CONFIG_PATH),
                       None)
        if cfg_mod is None:
            return []
        schema = self._parse_schema(cfg_mod)
        if not schema:
            return []
        findings = []
        by_path = {m.path: m for m in modules}

        def check_pair(mod: Module, node: ast.AST, sec: str, key: str,
                       require_section: bool) -> None:
            if sec not in schema:
                if require_section:
                    findings.append(self.finding(
                        mod, node,
                        f"unknown config section {sec!r} "
                        f"(sections: {', '.join(sorted(schema))})",
                    ))
                return
            if key not in schema[sec]:
                findings.append(self.finding(
                    mod, node,
                    f"config key {sec}.{key} is not in _SCHEMA "
                    "(serving/config.py) — typo or missing schema entry",
                ))

        # .get(section, key) through the call graph's typed receivers
        summary, _ = _summary_and_module(modules)
        for call in summary.attr_calls:
            if call.method != "get" or len(call.str_args) < 2 \
                    or None in call.str_args[:2]:
                continue
            typed_config = call.recv == self.CONFIG_CLASS
            named_config = (call.recv.startswith("name:")
                            and _CONFIGISH_RE.search(call.recv[5:]))
            if not (typed_config or named_config):
                continue
            mod = by_path.get(call.path)
            if mod is None:
                continue
            anchor = ast.Constant(value=0)
            anchor.lineno = call.lineno
            check_pair(mod, anchor, call.str_args[0], call.str_args[1],
                       require_section=typed_config)

        for mod in modules:
            in_config = mod.path == self.CONFIG_PATH
            for node in ast.walk(mod.tree):
                if in_config and isinstance(node, ast.Subscript) \
                        and isinstance(node.value, ast.Subscript):
                    outer, inner = node.slice, node.value.slice
                    if isinstance(inner, ast.Constant) \
                            and isinstance(inner.value, str) \
                            and isinstance(outer, ast.Constant) \
                            and isinstance(outer.value, str):
                        check_pair(mod, node, inner.value, outer.value,
                                   require_section=False)
                elif in_config and isinstance(node, ast.Tuple) \
                        and len(node.elts) == 2 \
                        and all(isinstance(e, ast.Constant)
                                and isinstance(e.value, str)
                                for e in node.elts):
                    check_pair(mod, node, node.elts[0].value,
                               node.elts[1].value, require_section=False)
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    for m in _ENV_KEY_RE.finditer(node.value):
                        sec, key = m.group(1).lower(), m.group(2).lower()
                        if sec not in schema:
                            findings.append(self.finding(
                                mod, node,
                                f"env var DIS_TPU_{m.group(1)}__"
                                f"{m.group(2)} names unknown config "
                                f"section {sec!r}",
                            ))
                        elif key not in schema[sec]:
                            findings.append(self.finding(
                                mod, node,
                                f"env var DIS_TPU_{m.group(1)}__"
                                f"{m.group(2)} names unknown key "
                                f"{sec}.{key}",
                            ))
        return findings


# ---------------------------------------------------------------------------
# DL014 — performance-telemetry catalog drift
# ---------------------------------------------------------------------------

# catalog rows in docs/OBSERVABILITY.md "Performance telemetry":
# | `name` | perf-field | ... |  /  | `name` | metric | ... |  /
# | `name` | digest | ... |
_PERF_CATALOG_ROW_RE = re.compile(
    r"^\|\s*`([a-z0-9_.{}<>]+)`\s*\|\s*(perf-field|metric|digest)\s*\|")


@register
class DL014(Rule):
    """Performance-telemetry catalog drift: the ``GET /server/perf``
    top-level fields, the telemetry metric names, and the digest series
    names are declared once in ``serving/teledigest.py`` (PERF_FIELDS /
    TELEMETRY_METRICS / DIGEST_NAMES — the constants the endpoint and
    tests are built against) and documented in the
    docs/OBSERVABILITY.md "Performance telemetry" tables. Both
    directions are enforced, like DL011's dual catalogs: a name in code
    but not in the docs is undocumented telemetry; a docs row with no
    code constant is a dead catalog entry. Every TELEMETRY_METRICS name
    must additionally be registered by a metric factory call in
    serving/metrics.py — a cataloged metric nobody registers is
    documentation describing a series that can never exist."""

    name = "DL014"
    title = "perf-telemetry catalog drift vs docs/OBSERVABILITY.md"
    severity = "P1"
    scope = "project"

    DOCS = "docs/OBSERVABILITY.md"
    TELEDIGEST_PATH = (
        "distributed_inference_server_tpu/serving/teledigest.py"
    )
    METRICS_PATH = "distributed_inference_server_tpu/serving/metrics.py"
    #: constant name -> catalog kind column
    CONSTS = {
        "PERF_FIELDS": "perf-field",
        "TELEMETRY_METRICS": "metric",
        "DIGEST_NAMES": "digest",
    }

    @staticmethod
    def _module_consts(mod: Module) -> Dict[str, Tuple[List[str], int]]:
        """{const_name: ([entries...], lineno)} for tuple/list string
        constants assigned at module level."""
        out: Dict[str, Tuple[List[str], int]] = {}
        for node in mod.tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            value = node.value
            if not names or not isinstance(value, (ast.Tuple, ast.List)):
                continue
            entries = [e.value for e in value.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str)]
            for name in names:
                out[name] = (entries, node.lineno)
        return out

    @staticmethod
    def _registered_metric_names(mod: Module) -> Set[str]:
        """Prometheus metric names registered anywhere in metrics.py
        (first string arg of a Counter/Gauge/Histogram/Summary call)."""
        names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func).rsplit(".", 1)[-1]
            if fname not in _METRIC_FACTORIES:
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                names.add(node.args[0].value)
        return names

    @staticmethod
    def _parse_catalog(path: Path) -> Dict[str, Tuple[str, int, str]]:
        out: Dict[str, Tuple[str, int, str]] = {}
        for i, line in enumerate(path.read_text().splitlines(), 1):
            m = _PERF_CATALOG_ROW_RE.match(line)
            if m:
                out[m.group(1)] = (m.group(2), i, line.strip())
        return out

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        tmod = next((m for m in modules
                     if m.path == self.TELEDIGEST_PATH), None)
        docs_path = root / self.DOCS
        if tmod is None or not docs_path.exists():
            return []  # nothing to drift (fixture roots)
        consts = self._module_consts(tmod)
        catalog = self._parse_catalog(docs_path)
        findings: List[Finding] = []

        def anchor(lineno: int) -> ast.AST:
            node = ast.Constant(value=0)
            node.lineno = lineno
            return node

        code_names: Dict[str, str] = {}
        for const, kind in self.CONSTS.items():
            entries, lineno = consts.get(const, ([], 1))
            for name in entries:
                code_names[name] = kind
                row = catalog.get(name)
                if row is None:
                    findings.append(self.finding(
                        tmod, anchor(lineno),
                        f"telemetry name {name!r} ({const}) is not in "
                        f"the {self.DOCS} \"Performance telemetry\" "
                        f"catalog — add a | `{name}` | {kind} | row or "
                        "drop the constant entry",
                    ))
                elif row[0] != kind:
                    findings.append(self.finding(
                        tmod, anchor(lineno),
                        f"telemetry name {name!r} is cataloged as kind "
                        f"{row[0]!r} but declared in {const} "
                        f"(kind {kind!r}) — the catalogs disagree",
                    ))
        for name, (kind, lineno, text) in sorted(catalog.items()):
            if name not in code_names:
                findings.append(Finding(
                    rule=self.name, path=self.DOCS, line=lineno,
                    message=f"cataloged {kind} name {name!r} is not "
                            "declared in serving/teledigest.py "
                            f"({', '.join(sorted(self.CONSTS))}) — dead "
                            "catalog entry or a lost declaration",
                    severity=self.severity, context="perf catalog",
                    line_text=text,
                ))

        mmod = next((m for m in modules if m.path == self.METRICS_PATH),
                    None)
        if mmod is not None:
            registered = self._registered_metric_names(mmod)
            entries, lineno = consts.get("TELEMETRY_METRICS", ([], 1))
            for name in entries:
                if name not in registered:
                    findings.append(self.finding(
                        tmod, anchor(lineno),
                        f"telemetry metric {name!r} is declared in "
                        "TELEMETRY_METRICS but never registered in "
                        "serving/metrics.py — the documented series "
                        "can never exist",
                    ))
        return findings


# ---------------------------------------------------------------------------
# DL015-DL018 — the v3 lifecycle layer: exactly-once registries,
# exception-edge resources, wire-handler exhaustiveness, fault-point
# coverage (docs/LINTS.md "distlint v3")
# ---------------------------------------------------------------------------

#: crash-path entry points by naming convention: the failure sweeps that
#: must be able to resolve every in-flight registry (``_fail_all``,
#: ``on_lost_requests``, ``_drop_connection``, ``close``, ...). The verb
#: must LEAD the name — ``record_expired`` and ``stop_health_loop`` are
#: bookkeeping, not sweeps — so the match is anchored
_CRASH_NAME_RE = re.compile(
    r"^_*(on_)?(fail|crash|lost|abort|drop|close|shutdown)")
#: what makes a dict attribute *in-flight* (entries carry continuations
#: that must run exactly once) rather than a state/telemetry map whose
#: entries expire or get overwritten: the codebase's own naming
#: convention — ``_inflight``, ``_pending_*``, mesh ``_live``,
#: ``_assemblies``, ``_export_jobs``, KV ``_streams`` — or an explicit
#: ``# distlint: registry`` marker on the declaration
_INFLIGHT_NAME_RE = re.compile(
    r"inflight|pending|live|waiter|assembl|resum|import|export|job|stream")
#: handoff methods whose call AFTER a pop re-opens the PR 7 window: the
#: popped entry is in neither the registry nor the engine while the
#: submit runs, so a concurrent crash sweep cannot resolve it
_HANDOFF_METHODS = frozenset({"submit", "submit_resume", "redispatch"})


@register
class DL015(Rule):
    """Exactly-once lifecycle for in-flight registries. A *registry* is
    a dict attribute following the codebase's pop-first convention —
    registered by a subscript/``setdefault`` add site, resolved by a
    ``pop``/``del``/``clear`` site, and recognizably *in-flight* by
    naming (``_inflight``/``_pending_*``/``_live``/``_assemblies``/...)
    — or any dict attribute whose declaration carries a
    ``# distlint: registry`` marker. State and telemetry maps are out:
    their entries expire or get overwritten, so there is no per-entry
    continuation to lose. Three checks, all scoped to ``serving/``
    (where the in-flight registries live):

    1. a registry with registrations but **no resolve site anywhere**
       leaks every entry (P0);
    2. **crash-path coverage**: when the owning class has crash-named
       methods (``_fail_all``/``close``/``_drop_connection``/...), some
       resolve site of the registry must be reachable from one of them
       through the call graph — otherwise entries leak past the failure
       sweep and their callbacks never run, the PR 2 ``submit_resume``
       bug (P0). Closures are invisible to the call graph, so the crash
       path must resolve at method level (which is also what makes it
       auditable);
    3. **pop-first gating** per function: popping an entry *before* the
       handoff (``submit``) re-opens the PR 7 ``_settle`` window (P0),
       and reading/membership-testing an entry before popping it is a
       check-then-act race where two callers can both see the entry and
       double-resolve (P1 — suppress with the single-thread argument
       where ownership makes it safe).

    The analysis under-approximates (closures skipped, unresolved
    receivers dropped): absence of a finding is not a proof."""

    name = "DL015"
    title = "in-flight registry entry can leak or double-resolve"
    severity = "P0"
    scope = "project"

    RESOLVE_OPS = frozenset({"pop", "del", "clear"})

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        from tools.lint import callgraph, threads

        summary, by_path = _summary_and_module(modules)
        ops_by_reg: Dict[Tuple[str, str], List] = {}
        for op in summary.registry_ops:
            ops_by_reg.setdefault((op.cls, op.attr), []).append(op)

        def is_registry(cls: str, attr: str) -> bool:
            if (cls, attr) in summary.registry_marks:
                return True
            if attr not in summary.class_dict_attrs.get(cls, set()):
                return False
            if not _INFLIGHT_NAME_RE.search(attr):
                # state/telemetry maps (member tables, health scores,
                # backoff clocks) expire or get overwritten — they have
                # no per-entry continuation to lose, so exactly-once
                # does not apply; mark the declaration to opt one in
                return False
            kinds = {o.op for o in ops_by_reg.get((cls, attr), ())}
            return "add" in kinds and bool(kinds & self.RESOLVE_OPS)

        handoffs: Dict[Tuple[str, str], List[int]] = {}
        for ac in summary.attr_calls:
            if ac.method in _HANDOFF_METHODS:
                handoffs.setdefault((ac.path, ac.context),
                                    []).append(ac.lineno)

        findings: List[Finding] = []
        regs = sorted(k for k in (set(ops_by_reg) | summary.registry_marks)
                      if is_registry(*k))
        for cls, attr in regs:
            if not cls.split("::", 1)[0].startswith(SERVING_PREFIX):
                continue  # in-flight registries live on the serving spine
            ops = ops_by_reg.get((cls, attr), [])
            reg_name = f"{callgraph.short(cls)}.{attr}"
            adds = [o for o in ops if o.op == "add"]
            resolves = [o for o in ops if o.op in self.RESOLVE_OPS]
            # (1) registered but never resolved, anywhere
            if adds and not resolves:
                a = min(adds, key=lambda o: (o.path, o.lineno))
                findings.append(_anchored(
                    self, by_path, a.path, a.lineno,
                    f"registry {reg_name} is registered here but has no "
                    "pop/del/clear resolve site anywhere — every entry "
                    "leaks",
                    context=callgraph.short(a.fn)))
                continue
            # (2) crash-path coverage over the call graph
            crash_fns = sorted(
                fid for fid, node in summary.functions.items()
                if node.cls == cls and _CRASH_NAME_RE.search(node.name))
            if adds and resolves and crash_fns:
                reach = threads.reachable(summary, crash_fns)
                if not any(o.fn in reach for o in resolves):
                    a = min(adds, key=lambda o: (o.path, o.lineno))
                    names = ", ".join(sorted({
                        summary.functions[f].name for f in crash_fns
                    })[:4])
                    findings.append(_anchored(
                        self, by_path, a.path, a.lineno,
                        f"registry {reg_name} has no resolve site on the "
                        f"crash path: none of {names} (nor anything they "
                        "call) pops/clears it, so entries registered "
                        "here survive the failure sweep and their "
                        "callbacks never run — drain it in the sweep, "
                        "or mark the declaration with the ownership "
                        "argument",
                        context=callgraph.short(a.fn)))
            # (3) per-function ordering: pop-before-handoff (P0) and
            # check-then-act read-before-pop (P1)
            by_fn: Dict[str, List] = {}
            for o in ops:
                by_fn.setdefault(o.fn, []).append(o)
            for fn, fn_ops in sorted(by_fn.items()):
                node = summary.functions.get(fn)
                if node is None or _CRASH_NAME_RE.search(node.name):
                    continue  # crash sweeps drain by design
                if node.name.endswith("_locked"):
                    # the repo's *_locked convention: the caller holds
                    # the class lock, so every op in here is atomic
                    # with respect to racing resolvers
                    continue
                pops = [o for o in fn_ops if o.op in ("pop", "del")]
                if not pops:
                    continue
                first_pop = min(pops, key=lambda o: o.lineno)
                for line in sorted(handoffs.get(
                        (node.path, callgraph.short(fn)), ())):
                    if line > first_pop.lineno:
                        findings.append(_anchored(
                            self, by_path, first_pop.path,
                            first_pop.lineno,
                            f"{reg_name} entry is popped before the "
                            f"handoff at line {line}: while the submit "
                            "runs, the entry is in neither the registry "
                            "nor the engine, so a concurrent crash "
                            "sweep cannot resolve it (the PR 7 "
                            "`_settle` window) — hand off first and pop "
                            "after, or re-register before the handoff",
                            context=callgraph.short(fn)))
                        break
                reads = [o for o in fn_ops
                         if o.op in ("get", "read", "contains")
                         and o.lineno < first_pop.lineno
                         # a lock held across both read and pop makes
                         # check-then-act atomic: no second resolver
                         # can interleave between them
                         and not (set(o.locks) & set(first_pop.locks))]
                if reads:
                    r = min(reads, key=lambda o: o.lineno)
                    findings.append(_anchored(
                        self, by_path, r.path, r.lineno,
                        f"resolution of {reg_name} is not pop-first "
                        f"gated: the read here precedes the pop at line "
                        f"{first_pop.lineno}, so two racing resolvers "
                        "can both observe the entry and double-resolve "
                        "it — pop first (one winner) and act on the "
                        "popped value, or suppress with the "
                        "single-owner argument",
                        context=callgraph.short(fn), severity="P1"))
        return findings


# -- DL016 ------------------------------------------------------------------

#: calls that cannot plausibly raise between an acquire and its release
#: (pure builtins, logging, collection accessors)
_DL016_SAFE_NAMES = frozenset({
    "len", "str", "int", "float", "bool", "min", "max", "isinstance",
    "getattr", "hasattr", "repr", "format", "sorted", "list", "dict",
    "set", "tuple", "id",
})
_DL016_SAFE_ATTRS = frozenset({
    "append", "get", "debug", "info", "warning", "error", "exception",
    "monotonic", "time", "items", "keys", "values", "copy", "strip",
    "split", "join", "lower", "upper", "format",
})
_DL016_RELEASE = {
    "socket": frozenset({"close", "shutdown", "detach"}),
    "span": frozenset({"finish", "end", "close"}),
    "import_session": frozenset({"abort", "commit", "publish", "close"}),
}
_DL016_BREAKER_SETTLE = frozenset({
    "release", "record_success", "record_failure"})
_DL016_DESC = {
    "socket": "dialed socket",
    "span": "tracer span",
    "breaker": "breaker half-open token",
    "import_session": "KV import session",
}


def _dl016_acquire_kind(call: ast.Call) -> Optional[str]:
    dotted = dotted_name(call.func)
    if dotted.endswith("create_connection") and "socket" in dotted:
        return "socket"
    if isinstance(call.func, ast.Attribute):
        recv_tail = dotted_name(call.func.value).rsplit(".", 1)[-1].lower()
        if call.func.attr == "start" and recv_tail == "tracer":
            return "span"
        if call.func.attr == "try_acquire" and "breaker" in recv_tail:
            return "breaker"
        if call.func.attr == "import_stream_open":
            return "import_session"
    return None


def _dl016_call_is_safe(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _DL016_SAFE_NAMES
    if isinstance(f, ast.Attribute):
        return f.attr in _DL016_SAFE_ATTRS
    return False


class _LifetimeScan:
    """One function body (nested defs skipped): resource acquires, the
    uses that settle them (release call / store / return / pass-along),
    every call site for the risky-region test, and per-node try/except/
    finally containment so protection is judged structurally."""

    def __init__(self) -> None:
        # {kind, var, lineno, end_lineno, trys, skip}
        self.acquires: List[Dict] = []
        # (var name, use kind, lineno, trys); kind is "stored" /
        # "returned" / "passed" / "method:<name>"
        self.uses: List[Tuple[str, str, int, Tuple]] = []
        # (receiver dotted, method, lineno, trys) — breaker settlement
        self.recv_calls: List[Tuple[str, str, int, Tuple]] = []
        # (lineno, is_safe, call node, trys)
        self.calls: List[Tuple[int, bool, ast.Call, Tuple]] = []
        self._consumed: Set[int] = set()

    def scan(self, fn_node) -> None:
        for stmt in fn_node.body:
            self._visit(stmt, ())

    # -- helpers -----------------------------------------------------------

    def _names_in(self, node: ast.AST) -> Set[str]:
        return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}

    def _record_acquire(self, kind: str, var: str, call: ast.Call,
                        trys: Tuple, skip=None) -> None:
        self.acquires.append({
            "kind": kind, "var": var, "lineno": call.lineno,
            "end_lineno": getattr(call, "end_lineno", call.lineno)
            or call.lineno,
            "trys": trys, "skip": skip,
        })
        self._consumed.add(id(call))

    # -- walk --------------------------------------------------------------

    def _visit(self, node: ast.AST, trys: Tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested defs are analyzed as their own functions
        if isinstance(node, ast.Try):
            tid = id(node)
            for s in node.body:
                self._visit(s, trys + ((tid, "body"),))
            for h in node.handlers:
                for s in h.body:
                    self._visit(s, trys + ((tid, "handler"),))
            for s in node.orelse:
                self._visit(s, trys + ((tid, "body"),))
            for s in node.finalbody:
                self._visit(s, trys + ((tid, "final"),))
            return
        self._classify(node, trys)
        for child in ast.iter_child_nodes(node):
            self._visit(child, trys)

    def _classify(self, node: ast.AST, trys: Tuple) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # a context manager owns its resource's lifecycle
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call) \
                            and _dl016_acquire_kind(sub):
                        self._consumed.add(id(sub))
            return
        if isinstance(node, ast.If):
            # the ``if not breaker.try_acquire(): <fail fast>`` guard:
            # the guarded body runs with NO token held — exclude it from
            # the risky region
            test = node.test
            neg = isinstance(test, ast.UnaryOp) \
                and isinstance(test.op, ast.Not)
            inner = test.operand if neg else test
            if isinstance(inner, ast.Call) \
                    and _dl016_acquire_kind(inner) == "breaker":
                skip = None
                if neg and node.body:
                    last = node.body[-1]
                    skip = (node.body[0].lineno,
                            getattr(last, "end_lineno", last.lineno)
                            or last.lineno)
                self._record_acquire(
                    "breaker", dotted_name(inner.func.value), inner,
                    trys, skip=skip)
            return
        if isinstance(node, ast.Assign):
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            if isinstance(value, ast.Call):
                kind = _dl016_acquire_kind(value)
                if kind == "breaker":
                    self._record_acquire(
                        "breaker", dotted_name(value.func.value), value,
                        trys)
                elif kind is not None:
                    tgt = node.targets[0] if len(node.targets) == 1 \
                        else None
                    if isinstance(tgt, ast.Name):
                        self._record_acquire(kind, tgt.id, value, trys)
                    else:
                        # stored into an attribute/subscript at birth:
                        # ownership transferred to the container
                        self._consumed.add(id(value))
            # ``self.x = var`` / ``self.d[k] = (var, ...)`` — transfer
            if not all(isinstance(t, ast.Name) for t in node.targets):
                for name in self._names_in(node.value):
                    self.uses.append((name, "stored", node.lineno, trys))
            return
        if isinstance(node, ast.Return):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and _dl016_acquire_kind(sub):
                    self._consumed.add(id(sub))  # returned at birth
            if node.value is not None:
                for name in self._names_in(node.value):
                    self.uses.append((name, "returned", node.lineno, trys))
            return
        if isinstance(node, ast.Call):
            kind = _dl016_acquire_kind(node)
            if kind is not None and id(node) not in self._consumed:
                if kind == "breaker":
                    self._record_acquire(
                        "breaker", dotted_name(node.func.value), node,
                        trys)
                # non-breaker acquires in expression position with no
                # binding (dropped result / passed as arg) are either
                # transferred or unobservable — skip both
                self._consumed.add(id(node))
            self.calls.append((node.lineno, _dl016_call_is_safe(node),
                               node, trys))
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for name in self._names_in(arg):
                    self.uses.append((name, "passed", node.lineno, trys))
            if isinstance(node.func, ast.Attribute):
                self.recv_calls.append((
                    dotted_name(node.func.value), node.func.attr,
                    node.lineno, trys))
                if isinstance(node.func.value, ast.Name):
                    self.uses.append((
                        node.func.value.id, f"method:{node.func.attr}",
                        node.lineno, trys))


@register
class DL016(Rule):
    """Exception-edge resource leak: an acquired resource — dialed
    socket, tracer span, KV import session, breaker half-open token —
    must be released, stored, returned, or handed to a callee on every
    path out of the acquiring function, *including the raise edges* of
    the calls between acquire and settlement. A call that can raise in
    that window needs the settlement in a ``finally``/``except`` of a
    ``try`` enclosing it; ``with`` acquires are exempt (the context
    manager is the settlement). Pass-along and store count as settling
    because ownership moved (the container's own lifecycle is DL015's
    problem). Per-function and syntactic — cross-thread settlement
    (e.g. a token resolved by a later callback) needs a suppression
    carrying the settlement argument."""

    name = "DL016"
    title = "acquired resource leaks on the exception edge"
    severity = "P1"
    scope = "project"

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in modules:
            if not mod.path.startswith(SERVING_PREFIX):
                continue
            for qual, fn_node in self._functions(mod.tree):
                findings.extend(self._check_fn(mod, qual, fn_node))
        return findings

    @staticmethod
    def _functions(tree: ast.Module):
        """Every def in the module — methods AND closures — with its
        qualname (closures settle resources for DL016 purposes exactly
        like named functions do)."""
        out: List[Tuple[str, ast.AST]] = []

        def walk(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{prefix}.{child.name}" if prefix \
                        else child.name
                    out.append((qual, child))
                    walk(child, qual)
                elif isinstance(child, ast.ClassDef):
                    walk(child, f"{prefix}.{child.name}" if prefix
                         else child.name)
                else:
                    walk(child, prefix)

        walk(tree, "")
        return out

    def _check_fn(self, mod: Module, qual: str,
                  fn_node) -> Iterable[Finding]:
        scan = _LifetimeScan()
        scan.scan(fn_node)
        findings: List[Finding] = []
        for acq in scan.acquires:
            kind, var = acq["kind"], acq["var"]
            if kind == "breaker":
                settles = [
                    (lineno, trys)
                    for recv, meth, lineno, trys in scan.recv_calls
                    if recv == var and meth in _DL016_BREAKER_SETTLE
                    and lineno > acq["end_lineno"]
                ]
            else:
                release = _DL016_RELEASE[kind]
                settles = [
                    (lineno, trys)
                    for name, use, lineno, trys in scan.uses
                    if name == var and lineno > acq["end_lineno"]
                    and (use in ("stored", "returned", "passed")
                         or (use.startswith("method:")
                             and use[len("method:"):] in release))
                ]
            desc = _DL016_DESC[kind]
            anchor = ast.Constant(value=0)
            anchor.lineno = acq["lineno"]
            if not settles:
                findings.append(self.finding(
                    mod, anchor,
                    f"{desc} acquired here is never released, stored, "
                    "returned, or passed on in this function — it leaks "
                    "on every path (or is settled cross-thread: "
                    "suppress with the settlement argument)",
                    context=qual))
                continue
            first = min(lineno for lineno, _t in settles)
            risky = [
                (c, trys) for lineno, safe, c, trys in scan.calls
                if not safe and acq["end_lineno"] < lineno < first
                and not (acq["skip"]
                         and acq["skip"][0] <= lineno <= acq["skip"][1])
            ]
            # a risky call is protected when some try enclosing it
            # settles the resource in its handler or finally
            protected_tids = {
                tid for _lineno, trys in settles
                for tid, region in trys if region in ("handler", "final")
            }
            exposed = [
                c for c, trys in risky
                if not any(tid in protected_tids
                           for tid, region in trys if region == "body")
            ]
            if exposed:
                worst = min(exposed, key=lambda c: c.lineno)
                findings.append(self.finding(
                    mod, anchor,
                    f"{desc} leaks on the exception edge: "
                    f"`{dotted_name(worst.func) or 'the call'}` at line "
                    f"{worst.lineno} can raise before the settlement at "
                    f"line {first} — release in a finally/except around "
                    "it, or move the handoff adjacent to the acquire",
                    context=qual))
        return findings


# -- DL017 ------------------------------------------------------------------

#: module-level frame-kind tables: ``FRAME_KINDS`` / ``KV_FRAME_KINDS``
_FRAME_TABLE_RE = re.compile(r"FRAME_KINDS$")
#: reader-loop marker: frame kinds this reader deliberately ignores
#: (one-way kinds that legally never arrive on this side of the wire)
_WIRE_IGNORES_MARK_RE = re.compile(
    r"#\s*distlint:\s*wire-ignores\[([A-Za-z0-9_,\s]+)\]")
_FRAME_KIND_NAME_RE = re.compile(r"^[A-Z][A-Za-z0-9]*$")


@register
class DL017(Rule):
    """Wire-handler exhaustiveness: every frame kind in a protowire
    table (``*FRAME_KINDS``) must have a dispatch arm in every reader
    loop fed by that table's ``recv_*`` function, or be declared
    deliberately ignored with ``# distlint: wire-ignores[KindA, KindB]``
    on the reader — the "added kind 6, missed a reader" drift DL005's
    schema check cannot see. Also flags the inverse (a dispatch arm or
    ignore entry naming a kind the table doesn't define: dead arm or
    typo) and an ``else: raise`` default on the dispatch chain (readers
    must tolerate unknown kinds so old peers survive new frames; the
    recv layer already rejects undecodable input)."""

    name = "DL017"
    title = "wire reader loop missing a frame-kind dispatch arm"
    severity = "P1"
    scope = "project"

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        from tools.lint.callgraph import _line_has_mark

        # frame-kind tables and the recv functions that decode them
        tables: Dict[str, Tuple[Module, Set[str]]] = {}
        for mod in modules:
            for node in mod.tree.body:
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name) \
                            and _FRAME_TABLE_RE.search(t.id) \
                            and isinstance(node.value, ast.Dict):
                        kinds = {
                            v.value for v in node.value.values
                            if isinstance(v, ast.Constant)
                            and isinstance(v.value, str)
                        }
                        if kinds:
                            tables[f"{mod.path}::{t.id}"] = (mod, kinds)
        recv_fns: Dict[str, str] = {}  # recv function name -> table key
        for mod in modules:
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    refs = {n.id for n in ast.walk(node)
                            if isinstance(n, ast.Name)}
                    for tkey in tables:
                        tpath, tname = tkey.split("::", 1)
                        if tpath == mod.path and tname in refs:
                            recv_fns[node.name] = tkey
        if not recv_fns:
            return []

        findings: List[Finding] = []
        for mod in modules:
            for qual, fn_node in DL016._functions(mod.tree):
                f = self._check_reader(mod, qual, fn_node, recv_fns,
                                       tables, _line_has_mark)
                findings.extend(f)
        return findings

    def _check_reader(self, mod: Module, qual: str, fn_node,
                      recv_fns: Dict[str, str],
                      tables: Dict[str, Tuple[Module, Set[str]]],
                      line_has_mark) -> List[Finding]:
        # which recv function does this reader drive, and which variable
        # binds the decoded frame name?
        tkey = None
        frame_vars: Set[str] = set()
        name_var = None
        for node in ast.walk(fn_node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            f = node.value.func
            fname = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if fname not in recv_fns:
                continue
            tkey = recv_fns[fname]
            tgt = node.targets[0] if node.targets else None
            if isinstance(tgt, ast.Name):
                frame_vars.add(tgt.id)
            elif isinstance(tgt, ast.Tuple) and tgt.elts \
                    and isinstance(tgt.elts[0], ast.Name):
                name_var = tgt.elts[0].id
        if tkey is None:
            return []
        if name_var is None and frame_vars:
            # ``frame = recv_x(...)`` then ``name, obj = frame``
            for node in ast.walk(fn_node):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in frame_vars \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Tuple) \
                        and node.targets[0].elts \
                        and isinstance(node.targets[0].elts[0], ast.Name):
                    name_var = node.targets[0].elts[0].id
                    break
        if name_var is None:
            return []  # not a dispatch loop (forwarding helper)

        table_mod, kinds = tables[tkey]
        tname = tkey.split("::", 1)[1]
        handled: Set[str] = set()
        intolerant: List[ast.AST] = []
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Compare) \
                    and isinstance(node.left, ast.Name) \
                    and node.left.id == name_var \
                    and len(node.ops) == 1:
                comp = node.comparators[0]
                if isinstance(node.ops[0], (ast.Eq, ast.NotEq)) \
                        and isinstance(comp, ast.Constant) \
                        and isinstance(comp.value, str):
                    handled.add(comp.value)
                elif isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                        and isinstance(comp, (ast.Tuple, ast.List,
                                              ast.Set)):
                    handled |= {
                        e.value for e in comp.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    }
            if isinstance(node, ast.If) \
                    and isinstance(node.test, ast.Compare) \
                    and isinstance(node.test.left, ast.Name) \
                    and node.test.left.id == name_var \
                    and node.orelse \
                    and not (len(node.orelse) == 1
                             and isinstance(node.orelse[0], ast.If)) \
                    and any(isinstance(s, ast.Raise)
                            for s in node.orelse):
                intolerant.append(node.orelse[0])

        m = line_has_mark(mod, fn_node.lineno, _WIRE_IGNORES_MARK_RE)
        ignores = ({x.strip() for x in m.group(1).split(",") if x.strip()}
                   if m else set())

        findings: List[Finding] = []
        for kind in sorted(kinds - handled - ignores):
            findings.append(self.finding(
                mod, fn_node,
                f"reader dispatches {tname} frames but has no arm for "
                f"kind {kind!r} — handle it, or declare the one-way "
                f"kind deliberate with "
                f"`# distlint: wire-ignores[{kind}]` on the reader",
                context=qual))
        for kind in sorted((handled | ignores) - kinds):
            if _FRAME_KIND_NAME_RE.match(kind):
                findings.append(self.finding(
                    mod, fn_node,
                    f"reader {'handles' if kind in handled else 'ignores'}"
                    f" frame kind {kind!r} which {tname} does not define "
                    "— dead dispatch arm or a typo",
                    context=qual))
        for node in intolerant:
            findings.append(self.finding(
                mod, node,
                f"dispatch on {tname} raises for unknown frame kinds — "
                "readers must tolerate kinds newer than they are (log "
                "and skip); the recv layer already rejects undecodable "
                "frames",
                context=qual))
        return findings


# -- DL018 ------------------------------------------------------------------


@register
class DL018(Rule):
    """Fault-point coverage drift: every point in the DL011 catalog (the
    serving/faults.py docstring) must be *exercised* — armed via a fault
    spec string in a chaos scenario (tools/chaos_fleet.py) or a
    committed test under tests/ — so a new injection point cannot ship
    with its failure path untested. DL011 keeps the catalog honest
    against the fire sites; this rule keeps the test surface honest
    against the catalog (docs/RESILIENCE.md cross-references both)."""

    name = "DL018"
    title = "cataloged fault point exercised by no scenario or test"
    severity = "P1"
    scope = "project"

    FAULTS_PATH = DL011.FAULTS_PATH
    CHAOS_PATH = "tools/chaos_fleet.py"
    TESTS_DIR = "tests"

    _POINT_KWARG_RE = re.compile(rf'point\s*=\s*["\']({_POINT_PAT})["\']')

    def _exercised(self, text: str) -> Set[str]:
        pts = {m.group(1) for m in _SPEC_POINT_RE.finditer(text)}
        pts |= {m.group(1) for m in self._POINT_KWARG_RE.finditer(text)}
        return pts

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        faults_mod = next(
            (m for m in modules if m.path == self.FAULTS_PATH), None)
        chaos_mod = next(
            (m for m in modules if m.path == self.CHAOS_PATH), None)
        if faults_mod is None or chaos_mod is None:
            return []  # file-restricted run: coverage needs the corpus
        catalog = set(_DOCSTRING_POINT_RE.findall(
            ast.get_docstring(faults_mod.tree) or ""))
        if not catalog:
            return []
        exercised = self._exercised("\n".join(chaos_mod.lines))
        tests_dir = root / self.TESTS_DIR
        if tests_dir.is_dir():
            for p in sorted(tests_dir.rglob("*.py")):
                try:
                    exercised |= self._exercised(p.read_text())
                except OSError:
                    continue

        def anchor_line(point: str) -> int:
            for i, line in enumerate(faults_mod.lines, 1):
                if point in line:
                    return i
            return 1

        findings: List[Finding] = []
        for point in sorted(catalog - exercised):
            line = anchor_line(point)
            findings.append(Finding(
                rule=self.name, path=faults_mod.path, line=line,
                message=f"cataloged fault point {point!r} is armed by no "
                        "chaos scenario (tools/chaos_fleet.py) and no "
                        "committed test — exercise it so the failure "
                        "path it guards stays covered",
                severity=self.severity, context="fault coverage",
                line_text=faults_mod.text(line),
            ))
        return findings
