"""distlint rules DL001-DL007 (catalog + rationale: docs/LINTS.md).

Each rule targets a failure class this codebase has actually hit or is
structurally exposed to: blocking calls on the serving spine, unlocked
shared state, silent exception swallowing, proto/wire drift, metric rot,
and host-side work leaking into the per-token decode loop.
"""

from __future__ import annotations

import ast
import importlib.util
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.lint import proto as protodef
from tools.lint.core import (
    Finding,
    Module,
    Rule,
    ScopedVisitor,
    dotted_name,
    register,
)

SERVING_PREFIX = "distributed_inference_server_tpu/serving/"

#: calls that block the calling thread, by dotted name
BLOCKING_DOTTED = frozenset({
    "time.sleep",
    "jax.device_get",
    "subprocess.run",
    "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection",
    "urllib.request.urlopen",
})
#: method names that block regardless of receiver
BLOCKING_ATTRS = frozenset({"block_until_ready"})
#: method names that block and are therefore forbidden un-awaited in
#: ``async def`` bodies (threading.Event.wait, Lock.acquire, Future.result)
ASYNC_BLOCKING_ATTRS = frozenset({"wait", "acquire", "result"})


def _is_blocking_call(node: ast.Call) -> Optional[str]:
    dotted = dotted_name(node.func)
    if dotted in BLOCKING_DOTTED:
        return dotted
    if isinstance(node.func, ast.Attribute) and node.func.attr in BLOCKING_ATTRS:
        return f".{node.func.attr}()"
    return None


# ---------------------------------------------------------------------------
# DL001 — blocking calls on async / serving-spine paths
# ---------------------------------------------------------------------------


@register
class DL001(Rule):
    """Blocking calls inside ``async def`` (anywhere) or raw ``time.sleep``
    / device syncs anywhere under ``serving/`` — the serving spine's
    threads must park on ``Event.wait`` (interruptible, shutdown-aware)
    and its coroutines on ``asyncio.sleep``/executors."""

    name = "DL001"
    title = "blocking call on an async or serving-spine path"
    severity = "P0"

    def check(self, module: Module) -> Iterable[Finding]:
        rule = self
        findings: List[Finding] = []
        in_serving = module.path.startswith(SERVING_PREFIX)

        class V(ScopedVisitor):
            def __init__(self) -> None:
                super().__init__()
                self._awaited: Set[int] = set()

            def visit_Await(self, node: ast.Await) -> None:
                self._awaited.add(id(node.value))
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call) -> None:
                blocked = _is_blocking_call(node)
                if self.in_async and id(node) not in self._awaited:
                    name = blocked
                    if (name is None and isinstance(node.func, ast.Attribute)
                            and node.func.attr in ASYNC_BLOCKING_ATTRS):
                        name = f".{node.func.attr}()"
                    if name is not None:
                        findings.append(rule.finding(
                            module, node,
                            f"blocking call {name} inside async def "
                            f"{self.func_name} — await an async equivalent "
                            "or offload via run_in_executor",
                            context=self.qualname,
                        ))
                elif in_serving and blocked is not None:
                    findings.append(rule.finding(
                        module, node,
                        f"blocking call {blocked} on the serving spine — "
                        "use Event.wait (shutdown-aware) or move off the "
                        "dispatch path; suppress with a justification if "
                        "this thread legitimately sleeps",
                        context=self.qualname,
                        severity="P1",
                    ))
                self.generic_visit(node)

        V().visit(module.tree)
        return findings


# ---------------------------------------------------------------------------
# DL002 — mutation of lock-guarded shared state outside the lock
# ---------------------------------------------------------------------------

_LOCK_FACTORY_RE = re.compile(r"(^|\.)(Lock|RLock|Condition)$")
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "add", "clear", "update",
    "setdefault", "sort", "reverse",
})


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> "X" (else None)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _mutated_attrs(stmt: ast.AST) -> Set[str]:
    """self attributes mutated by one statement: assignment to ``self.X``
    or ``self.X[...]``, ``self.X <op>= ...``, or ``self.X.<mutator>(...)``."""
    out: Set[str] = set()

    def target_attr(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                target_attr(el)
            return
        a = _self_attr(t)
        if a is not None:
            out.add(a)
            return
        if isinstance(t, ast.Subscript):
            a = _self_attr(t.value)
            if a is not None:
                out.add(a)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            target_attr(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        target_attr(stmt.target)
    elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            a = _self_attr(f.value)
            if a is not None:
                out.add(a)
    return out


def _with_locks(node: ast.AST, lock_attrs: Set[str]) -> Set[str]:
    """Lock attrs entered by a With statement (``with self._lock: ...``)."""
    out: Set[str] = set()
    if isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            a = _self_attr(item.context_expr)
            if a is not None and a in lock_attrs:
                out.add(a)
    return out


@register
class DL002(Rule):
    """For classes that own a ``threading.Lock``/``RLock``/``Condition``:
    any attribute ever mutated under the lock is *guarded*; mutating a
    guarded attribute outside a ``with self.<lock>:`` block (outside
    ``__init__``) is a data race waiting for load.

    Convention: methods named ``*_locked`` declare "caller holds the
    lock" and are exempt — the analysis is intra-procedural and cannot
    see the caller's ``with`` block."""

    name = "DL002"
    title = "guarded shared state mutated outside its lock"
    severity = "P1"

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(module.tree)
                    if isinstance(n, ast.ClassDef)]:
            findings.extend(self._check_class(module, cls))
        return findings

    def _methods(self, cls: ast.ClassDef):
        return [n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _check_class(self, module: Module,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        lock_attrs: Set[str] = set()
        for meth in self._methods(cls):
            for stmt in ast.walk(meth):
                if not isinstance(stmt, ast.Assign):
                    continue
                if not isinstance(stmt.value, ast.Call):
                    continue
                if not _LOCK_FACTORY_RE.search(dotted_name(stmt.value.func)):
                    continue
                for t in stmt.targets:
                    a = _self_attr(t)
                    if a is not None:
                        lock_attrs.add(a)
        if not lock_attrs:
            return []

        # pass 1: attrs mutated while holding any of this class's locks
        guarded: Set[str] = set()
        for meth in self._methods(cls):
            for attr, _node, held in self._iter_mutations(meth, lock_attrs):
                if held:
                    guarded.add(attr)
        guarded -= lock_attrs
        if not guarded:
            return []

        # pass 2: mutations of guarded attrs with no lock held
        findings: List[Finding] = []
        for meth in self._methods(cls):
            if meth.name == "__init__" or meth.name.endswith("_locked"):
                continue
            for attr, node, held in self._iter_mutations(meth, lock_attrs):
                if attr in guarded and not held:
                    findings.append(self.finding(
                        module, node,
                        f"self.{attr} is mutated under "
                        f"{'/'.join(sorted(lock_attrs))} elsewhere but "
                        f"written here without the lock",
                        context=f"{cls.name}.{meth.name}",
                    ))
        return findings

    def _iter_mutations(self, meth, lock_attrs: Set[str]):
        """Yield (attr, node, lock_held) for each self-attr mutation in the
        method body. Nested function defs are skipped: closures run later,
        on other threads, under their own discipline."""

        def walk(node: ast.AST, held: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                child_held = held or bool(_with_locks(child, lock_attrs))
                for attr in _mutated_attrs(child):
                    yield attr, child, child_held
                yield from walk(child, child_held)

        yield from walk(meth, False)


# ---------------------------------------------------------------------------
# DL003 — lock held across await / blocking call
# ---------------------------------------------------------------------------

_LOCKISH_NAME_RE = re.compile(r"lock|mutex|cond|(^|_)cv$", re.IGNORECASE)


@register
class DL003(Rule):
    """Inside ``with <lock>:`` — where the context expression *names* a
    lock (``_lock``, ``_cv``, ``mutex`` ...) — an ``await`` or a blocking
    call serializes every other thread/task on that lock for the full
    duration. Calls on the lock object itself (``cv.wait``) are exempt:
    Condition.wait releases the lock."""

    name = "DL003"
    title = "lock held across await or blocking call"
    severity = "P0"

    _HELD_BLOCKING_ATTRS = frozenset(
        {"wait", "join", "acquire", "result"} | set(BLOCKING_ATTRS)
    )

    def check(self, module: Module) -> Iterable[Finding]:
        rule = self
        findings: List[Finding] = []

        class V(ScopedVisitor):
            def _visit_with(self, node) -> None:
                lock_exprs = [
                    item.context_expr for item in node.items
                    if _LOCKISH_NAME_RE.search(
                        dotted_name(item.context_expr).rsplit(".", 1)[-1])
                ]
                if lock_exprs:
                    self._scan_body(node, lock_exprs)
                self.generic_visit(node)

            visit_With = _visit_with
            visit_AsyncWith = _visit_with

            def _scan_body(self, with_node, lock_exprs) -> None:
                lock_dumps = {ast.dump(e) for e in lock_exprs}
                lock_names = " / ".join(dotted_name(e) or "<lock>"
                                        for e in lock_exprs)

                def walk(node: ast.AST) -> None:
                    for child in ast.iter_child_nodes(node):
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)):
                            continue
                        if isinstance(child, ast.Await):
                            findings.append(rule.finding(
                                module, child,
                                f"await while holding {lock_names}",
                                context=self.qualname,
                            ))
                        elif isinstance(child, ast.Call):
                            self._check_call(child, lock_dumps, lock_names)
                        walk(child)

                for stmt in with_node.body:
                    walk(stmt)

            def _check_call(self, node: ast.Call, lock_dumps,
                            lock_names) -> None:
                blocked = _is_blocking_call(node)
                if (blocked is None
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in rule._HELD_BLOCKING_ATTRS):
                    # calls on the held lock itself are the exemption
                    if ast.dump(node.func.value) in lock_dumps:
                        return
                    blocked = f".{node.func.attr}()"
                if blocked is not None:
                    findings.append(rule.finding(
                        module, node,
                        f"blocking call {blocked} while holding "
                        f"{lock_names}",
                        context=self.qualname,
                    ))

        V().visit(module.tree)
        return findings


# ---------------------------------------------------------------------------
# DL004 — silently swallowed broad excepts
# ---------------------------------------------------------------------------

_LOG_METHODS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical", "log",
    "warn",
})
_COUNTERISH_RE = re.compile(r"drop|err|fail|count|total", re.IGNORECASE)


@register
class DL004(Rule):
    """``except Exception`` / bare ``except`` whose handler neither
    re-raises, nor logs, nor increments an error counter, nor *uses* the
    caught exception (forwarding ``e`` into a sink/callback/state counts
    as handling) — the error vanishes and only a soak test will find it."""

    name = "DL004"
    title = "broad except swallows the error silently"
    severity = "P1"

    def check(self, module: Module) -> Iterable[Finding]:
        rule = self
        findings: List[Finding] = []

        class V(ScopedVisitor):
            def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
                if rule._is_broad(node.type) and not rule._handled(node):
                    kind = ("bare except" if node.type is None
                            else "except Exception")
                    findings.append(rule.finding(
                        module, node,
                        f"{kind} swallows the error: add logging, an "
                        "errors_total increment, or a re-raise (or forward "
                        "the exception into the failure path)",
                        context=self.qualname,
                    ))
                self.generic_visit(node)

        V().visit(module.tree)
        return findings

    @staticmethod
    def _is_broad(t: Optional[ast.expr]) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(DL004._is_broad(el) for el in t.elts)
        return (isinstance(t, ast.Name)
                and t.id in ("Exception", "BaseException"))

    @staticmethod
    def _handled(handler: ast.ExceptHandler) -> bool:
        var = handler.name
        for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Name) and node.id == var:
                return True  # exception object forwarded / recorded
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func)
                if isinstance(node.func, ast.Attribute):
                    if node.func.attr in _LOG_METHODS:
                        return True
                    if node.func.attr == "inc":
                        return True
                if "record_" in dotted or "metric" in dotted:
                    return True
                if dotted.startswith("warnings.warn"):
                    return True
            if (isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Add)):
                tgt = node.target
                if (isinstance(tgt, ast.Attribute)
                        and _COUNTERISH_RE.search(tgt.attr)):
                    return True  # fail-open counter (e.g. otlp dropped)
        return False


# ---------------------------------------------------------------------------
# DL005 — proto <-> protowire drift
# ---------------------------------------------------------------------------


def compare_wire_schema(
    schema: protodef.ProtoSchema,
    messages: Dict[str, Dict[int, Tuple[str, str, str]]],
    enums: Dict[str, Dict[int, Optional[str]]],
) -> List[Tuple[str, str]]:
    """Cross-check the parsed proto schema against protowire's tables.
    Returns ``(anchor, message)`` pairs; anchor is the message/enum name
    the finding attaches to. Pure so tests can inject drifted tables."""
    out: List[Tuple[str, str]] = []

    for name in sorted(set(schema.messages) - set(messages)):
        out.append((name, f"message {name} is in inference.proto but has "
                          "no protowire codec entry"))
    for name in sorted(set(messages) - set(schema.messages)):
        out.append((name, f"protowire codec defines message {name} absent "
                          "from inference.proto"))

    for name in sorted(set(schema.messages) & set(messages)):
        pmsg = schema.messages[name]
        table = messages[name]
        for num in sorted(set(pmsg.fields) - set(table)):
            f = pmsg.fields[num]
            out.append((name, f"{name}: proto field {f.name} = {num} "
                              "missing from the protowire table"))
        for num in sorted(set(table) - set(pmsg.fields)):
            out.append((name, f"{name}: protowire field number {num} "
                              f"({table[num][0]!r}) not in inference.proto"))
        for num in sorted(set(pmsg.fields) & set(table)):
            pf = pmsg.fields[num]
            tname, ttype, tcard = table[num]
            if pf.name != tname:
                out.append((name, f"{name}.{num}: name drift — proto "
                                  f"{pf.name!r} vs protowire {tname!r}"))
            kind, expect_type = protodef.resolve_type(schema, name, pf.type)
            if kind == "unknown":
                out.append((name, f"{name}.{pf.name}: unresolvable proto "
                                  f"type {pf.type!r}"))
                continue
            if expect_type != ttype:
                out.append((name, f"{name}.{pf.name}: type drift — proto "
                                  f"{pf.type} (-> {expect_type}) vs "
                                  f"protowire {ttype!r}"))
            # proto3 singular message fields have explicit presence
            expect_card = pf.label
            if kind == "msg" and expect_card == "one":
                expect_card = "opt"
            if expect_card != tcard:
                out.append((name, f"{name}.{pf.name}: cardinality drift — "
                                  f"proto {expect_card!r} vs protowire "
                                  f"{tcard!r}"))

    for name in sorted(set(schema.enums) - set(enums)):
        out.append((name, f"enum {name} missing from protowire ENUMS"))
    for name in sorted(set(enums) - set(schema.enums)):
        out.append((name, f"protowire enum {name} absent from "
                          "inference.proto"))
    for name in sorted(set(schema.enums) & set(enums)):
        penum = schema.enums[name]
        table = enums[name]
        nonzero = {n: v for n, v in penum.values.items() if n != 0}
        for num in sorted(set(nonzero) - set(k for k in table if k != 0)):
            out.append((name, f"enum {name}: value {nonzero[num]} = {num} "
                              "missing from protowire"))
        for num in sorted(set(table) - set(penum.values) - {0}):
            out.append((name, f"enum {name}: protowire value {num} not in "
                              "inference.proto"))
        for num, vname in sorted(nonzero.items()):
            if num in table and table[num] != vname.lower():
                out.append((name, f"enum {name}.{vname}: JSON string drift "
                                  f"— expected {vname.lower()!r}, protowire "
                                  f"has {table[num]!r}"))
    return out


def load_protowire_tables(root: Path):
    """Import serving/protowire.py standalone (stdlib-only module) and
    return its (MESSAGES, ENUMS)."""
    path = (root / "distributed_inference_server_tpu" / "serving"
            / "protowire.py")
    spec = importlib.util.spec_from_file_location("_distlint_protowire", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    return mod.MESSAGES, mod.ENUMS


@register
class DL005(Rule):
    """The hand-rolled codec tables in serving/protowire.py must agree
    field-for-field with the authoritative contract in
    serving/inference.proto — field numbers, names, types, cardinality,
    enum values. Drift here corrupts KV handoffs and gRPC payloads
    silently (the varint still decodes — into the wrong thing)."""

    name = "DL005"
    title = "wire drift between inference.proto and protowire.py"
    severity = "P0"
    scope = "project"

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        proto_path = (root / "distributed_inference_server_tpu" / "serving"
                      / "inference.proto")
        wire_rel = "distributed_inference_server_tpu/serving/protowire.py"
        wire_mod = next((m for m in modules if m.path == wire_rel), None)
        if not proto_path.exists() or wire_mod is None:
            return []
        schema = protodef.parse_file(proto_path)
        messages, enums = load_protowire_tables(root)
        findings = []
        for anchor, msg in compare_wire_schema(schema, messages, enums):
            findings.append(Finding(
                rule=self.name, path=wire_rel,
                line=self._anchor_line(wire_mod, anchor),
                message=msg, severity=self.severity, context=anchor,
                line_text=wire_mod.text(self._anchor_line(wire_mod, anchor)),
            ))
        return findings

    @staticmethod
    def _anchor_line(module: Module, name: str) -> int:
        pat = f'"{name}"'
        for i, line in enumerate(module.lines, 1):
            if pat in line:
                return i
        return 1


# ---------------------------------------------------------------------------
# DL006 — metric hygiene
# ---------------------------------------------------------------------------

_METRIC_FACTORIES = frozenset({"Counter", "Gauge", "Histogram", "Summary"})


@register
class DL006(Rule):
    """Every metric registered on MetricsCollector must be emitted by some
    recording method, every public recording method must be called from
    the serving stack, and every ``*.metrics.<attr>`` access must resolve
    to a real collector attribute (no phantom metrics, no typo'd
    emission sites)."""

    name = "DL006"
    title = "metric registered/emitted mismatch"
    severity = "P1"
    scope = "project"

    METRICS_PATH = "distributed_inference_server_tpu/serving/metrics.py"

    def check_project(self, modules: Sequence[Module],
                      root: Path) -> Iterable[Finding]:
        mmod = next((m for m in modules if m.path == self.METRICS_PATH), None)
        if mmod is None:
            return []
        cls = next((n for n in ast.walk(mmod.tree)
                    if isinstance(n, ast.ClassDef)
                    and n.name == "MetricsCollector"), None)
        if cls is None:
            return []

        metric_attrs: Dict[str, ast.AST] = {}
        prom_names: Dict[str, ast.AST] = {}
        findings: List[Finding] = []
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is not None:
            for node in ast.walk(init):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                fname = dotted_name(node.value.func).rsplit(".", 1)[-1]
                if fname not in _METRIC_FACTORIES:
                    continue
                attr = _self_attr(node.targets[0]) if node.targets else None
                if attr is None:
                    continue
                metric_attrs[attr] = node
                args = node.value.args
                if args and isinstance(args[0], ast.Constant) \
                        and isinstance(args[0].value, str):
                    pname = args[0].value
                    if pname in prom_names:
                        findings.append(self.finding(
                            mmod, node,
                            f"duplicate prometheus metric name {pname!r}",
                            context="MetricsCollector.__init__",
                        ))
                    prom_names[pname] = node

        methods = {n.name for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        public_methods = {m for m in methods if not m.startswith("_")}
        # module-level names of metrics.py are legal accesses through a
        # `metrics` module alias (EngineStatus etc.)
        module_names = {n.name for n in mmod.tree.body
                        if isinstance(n, (ast.ClassDef, ast.FunctionDef))}
        allowed = set(metric_attrs) | methods | module_names | {"registry"}

        # reads of self.<metric attr> inside metrics.py (emission sites)
        internal_reads: Set[str] = set()
        for node in ast.walk(cls):
            a = _self_attr(node)
            if a is not None and isinstance(node.ctx, ast.Load):
                internal_reads.add(a)

        # accesses through a receiver *named* metrics, package-wide
        external: Dict[str, List[Tuple[Module, ast.AST]]] = {}
        for mod in modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                recv = node.value
                is_metrics_recv = (
                    (isinstance(recv, ast.Name) and recv.id == "metrics")
                    or (isinstance(recv, ast.Attribute)
                        and recv.attr == "metrics")
                )
                if is_metrics_recv:
                    external.setdefault(node.attr, []).append((mod, node))

        for attr, sites in sorted(external.items()):
            if attr not in allowed:
                mod, node = sites[0]
                findings.append(self.finding(
                    mod, node,
                    f"metrics.{attr} does not exist on MetricsCollector "
                    "(typo'd emission site or unregistered metric)",
                ))

        for attr, node in sorted(metric_attrs.items()):
            if attr not in internal_reads and attr not in external:
                findings.append(self.finding(
                    mmod, node,
                    f"metric self.{attr} is registered but never emitted",
                    context="MetricsCollector.__init__",
                ))

        for meth in sorted(public_methods):
            if meth in ("snapshot", "prometheus_text"):
                continue  # rendering surface, exercised by transports/tests
            if meth not in external:
                node = next(n for n in cls.body
                            if isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))
                            and n.name == meth)
                findings.append(self.finding(
                    mmod, node,
                    f"MetricsCollector.{meth} is never called from the "
                    "serving stack — dead recording surface",
                    context=f"MetricsCollector.{meth}",
                ))
        return findings


# ---------------------------------------------------------------------------
# DL007 — JAX hot-path hygiene in the per-token decode loop
# ---------------------------------------------------------------------------


@register
class DL007(Rule):
    """The per-token emission path in engine/engine.py (HOT_FUNCTIONS)
    runs once per generated token on the host: a ``jnp.*`` call allocates
    device memory / dispatches XLA work there, and an explicit sync
    (``device_get`` / ``block_until_ready`` / ``.item()``) stalls the
    decode pipeline. Device reads belong at the block boundary
    (``np.asarray`` on the block's outputs, once per block)."""

    name = "DL007"
    title = "device work inside the per-token decode loop"
    severity = "P0"

    TARGET = "distributed_inference_server_tpu/engine/engine.py"
    HOT_FUNCTIONS = frozenset({
        "_process_block", "_drain_pending", "_emit_token", "_decode_piece",
        "_flush_pending_text", "_finish",
    })
    _SYNC_ATTRS = frozenset({"block_until_ready", "item"})

    def check(self, module: Module) -> Iterable[Finding]:
        if module.path != self.TARGET:
            return []
        rule = self
        findings: List[Finding] = []

        class V(ScopedVisitor):
            def visit_Call(self, node: ast.Call) -> None:
                if rule.HOT_FUNCTIONS & set(self._stack):
                    dotted = dotted_name(node.func)
                    bad = None
                    if dotted.startswith("jnp.") \
                            or dotted.startswith("jax.numpy."):
                        bad = f"{dotted} (device allocation/dispatch)"
                    elif dotted == "jax.device_get":
                        bad = "jax.device_get (host sync)"
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in rule._SYNC_ATTRS):
                        bad = f".{node.func.attr}() (host sync)"
                    if bad is not None:
                        findings.append(rule.finding(
                            module, node,
                            f"{bad} inside the per-token decode loop "
                            f"({self.func_name}) — hoist to the block "
                            "boundary",
                            context=self.qualname,
                        ))
                self.generic_visit(node)

        V().visit(module.tree)
        return findings
