"""distlint CLI — ``python -m tools.lint.run``.

Exit status: 0 when every finding is suppressed inline or matched by the
baseline; 1 otherwise (and 1 on ``--check-stale`` when baseline entries no
longer match anything — the baseline may only shrink, docs/LINTS.md).

Modes:
    python -m tools.lint.run                   # whole package
    python -m tools.lint.run --changed         # only files touched in git
    python -m tools.lint.run --update-baseline # re-grandfather P1 findings
    python -m tools.lint.run --list-rules
    python -m tools.lint.run --json            # machine-readable findings
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import List, Optional

from tools.lint import rules as _rules  # noqa: F401 — populates RULES
from tools.lint.core import (
    BASELINE_PATH,
    DEFAULT_TARGET,
    EXTRA_TARGETS,
    RULES,
    apply_baseline,
    load_baseline,
    run_lint,
    save_baseline,
)

REPO_ROOT = Path(__file__).resolve().parent.parent.parent


def _is_lint_target(path: str) -> bool:
    if not path.endswith(".py"):
        return False
    if path.startswith(DEFAULT_TARGET + "/"):
        return True
    return any(path == t or path.startswith(t + "/")
               for t in EXTRA_TARGETS)


def changed_files(root: Path) -> Optional[List[str]]:
    """Lint-target .py files touched per git (staged, unstaged,
    untracked). None (= lint everything) when git is unavailable."""
    try:
        # -uall: plain porcelain collapses a new directory to one
        # "?? dir/" entry, which would hide every .py inside it
        out = subprocess.run(
            ["git", "status", "--porcelain", "-uall"], cwd=root,
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout
    except (OSError, subprocess.SubprocessError):
        return None
    files = []
    for line in out.splitlines():
        path = line[3:].split(" -> ")[-1].strip().strip('"')
        if _is_lint_target(path):
            files.append(path)
    return files


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="distlint", description=__doc__)
    ap.add_argument("files", nargs="*",
                    help="repo-relative files (default: whole package)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only git-modified package files (fast "
                         "pre-commit mode; project-scope rules still run)")
    ap.add_argument("--rule", action="append", dest="rules",
                    help="run only this rule (repeatable)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default {BASELINE_PATH})")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current non-P0 findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--check-stale", action="store_true",
                    help="also fail on baseline entries that match nothing")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--format", choices=("text", "github"), default="text",
                    help="'github' additionally emits ::error workflow "
                         "annotations so findings surface inline on PRs")
    ap.add_argument("--timings", action="store_true",
                    help="print per-rule wall time after the run (the "
                         "first project rule pays the shared callgraph "
                         "build; docs/LINTS.md budgets the full run)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            r = RULES[name]
            print(f"{name}  [{r.severity}/{r.scope}]  {r.title}")
        return 0

    files: Optional[List[str]] = args.files or None
    if args.changed and files is None:
        files = changed_files(REPO_ROOT)

    if args.update_baseline and (files is not None or args.rules):
        # a partial run sees only a subset of findings; rewriting the
        # baseline from it would silently drop grandfathered entries for
        # every unscanned file or unselected rule
        print("distlint: --update-baseline requires a full run "
              "(drop --changed / --rule / file arguments)")
        return 2

    timings = {} if args.timings else None
    if files is not None:
        # file-restricted mode: module-scope rules see only the named
        # files, but project-scope rules (proto drift, metric hygiene)
        # are cross-file — they must always see the whole package or
        # "emitted somewhere" checks false-positive on the subset
        names = args.rules or sorted(RULES)
        mod_rules = [n for n in names if RULES[n].scope == "module"]
        proj_rules = [n for n in names if RULES[n].scope == "project"]
        active, suppressed = run_lint(REPO_ROOT, files=files,
                                      rules=mod_rules or None,
                                      timings=timings) \
            if mod_rules else ([], [])
        if proj_rules:
            pa, ps = run_lint(REPO_ROOT, files=None, rules=proj_rules,
                              timings=timings)
            active, suppressed = active + pa, suppressed + ps
    else:
        active, suppressed = run_lint(REPO_ROOT, files=None,
                                      rules=args.rules, timings=timings)

    if args.update_baseline:
        keep = [f for f in active if f.severity != "P0"]
        p0 = [f for f in active if f.severity == "P0"]
        save_baseline(keep, args.baseline)
        print(f"baseline written: {len(keep)} entries "
              f"({args.baseline or BASELINE_PATH})")
        for f in p0:
            print(f"NOT baselined (P0 must be fixed): {f.render()}")
        return 1 if p0 else 0

    baseline = [] if args.no_baseline else load_baseline(args.baseline)
    new, grandfathered, stale = apply_baseline(active, baseline)

    if args.as_json:
        print(json.dumps({
            "findings": [f.__dict__ for f in new],
            "grandfathered": [f.__dict__ for f in grandfathered],
            "suppressed": len(suppressed),
            "stale_baseline": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
            if args.format == "github":
                # workflow-command annotation: one line, message sanitized
                # per the docs (%, CR, LF escaped)
                msg = (f.message.replace("%", "%25").replace("\r", "%0D")
                       .replace("\n", "%0A"))
                print(f"::error file={f.path},line={f.line},"
                      f"title=distlint {f.rule}[{f.severity}]::{msg}")
        if new:
            print(f"\ndistlint: {len(new)} finding(s) "
                  f"({len(grandfathered)} baselined, "
                  f"{len(suppressed)} suppressed inline)")
        else:
            print(f"distlint: clean ({len(grandfathered)} baselined, "
                  f"{len(suppressed)} suppressed inline)")
        if stale and args.check_stale:
            print(f"distlint: {len(stale)} stale baseline entr(y/ies) — "
                  "shrink tools/lint/baseline.json:")
            for e in stale:
                print(f"  stale: {e['rule']} {e['path']} :: {e['line']}")
            if args.format == "github":
                print("::error file=tools/lint/baseline.json::"
                      f"{len(stale)} baseline entr(y/ies) no longer match "
                      "any finding — the baseline may only shrink "
                      "(docs/LINTS.md)")
    if timings is not None:
        total = sum(timings.values())
        print("\ndistlint timings (wall seconds; the first project rule "
              "pays the shared callgraph build):")
        for name, secs in sorted(timings.items(),
                                 key=lambda kv: -kv[1]):
            print(f"  {name:<10} {secs:7.3f}s")
        print(f"  {'total':<10} {total:7.3f}s")
    rc = 1 if new else 0
    if args.check_stale and stale:
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
